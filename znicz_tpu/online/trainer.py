"""The continual trainer: replayed traffic → blessed checkpoints →
candidates.

``python -m znicz_tpu online-train`` runs this next to a serving
process: warm-start from the artifact the fleet is serving, fine-tune
on replayed capture-log traffic in **bounded rounds**, judge every
round against a held-back slice, and commit only *blessed* results —
through the existing :class:`~znicz_tpu.parallel.checkpoint.
TrainerCheckpointer` manifest protocol (so PR 6's
:class:`~znicz_tpu.promotion.sources.CheckpointSource` sees them) and
as atomically-committed candidate ``.znn`` files (so the stock
``promote`` CLI's ``DirectorySource`` → canary → SLO watch → fleet
walk picks them up with **zero new promotion code**).

A round's lifecycle::

    gather (bounded poll of the replay window)
      ├─ too cold ───────────────▶ "starved"  (no training, no block)
      └─ train K epochs on the round window (labels = served argmax)
           └─ evaluate candidate vs the CURRENT blessed params on the
              held-back slice (same slice, same batch — a fair race)
                ├─ regression beyond tolerance, or non-finite
                │     ─▶ "refused": params revert to the blessed
                │        snapshot (poison must not compound) and
                │        nothing is exported
                └─ within tolerance ─▶ "blessed": checkpoint step
                   (durability manifest = the bless mark) + candidate
                   export

The tolerance judgment is relative — candidate loss may not exceed
``blessed loss × (1 + tol) + abs_tol`` on the held-back slice — the
same delta-not-absolute stance as the BASELINE convergence contracts
(an online stream has no fixed target accuracy, but "no worse than
what is already serving" is always well-defined).
"""

from __future__ import annotations

import logging
import os

import numpy as np

from .. import export as export_mod
from ..export import ACT, KIND, _commit_znn, _pack_layer, _write_header
from ..parallel.fused import FusedTrainer, LayerSpec, ModelSpec
from ..telemetry.registry import REGISTRY
from .replay import ReplayReader, records_to_arrays

log = logging.getLogger("online")

_rounds = REGISTRY.counter(
    "online_rounds_total",
    "continual-training rounds driven to an outcome (blessed = "
    "checkpoint + candidate committed | refused = held-back eval "
    "regressed beyond tolerance, params reverted | starved = the "
    "replay window was too cold to train, degraded honestly)")
_eval_g = REGISTRY.gauge(
    "online_eval_loss",
    "held-back-slice loss of the most recent round's candidate "
    "(compare against online_blessed_eval_loss to see the margin the "
    "bless judgment had)")
_blessed_g = REGISTRY.gauge(
    "online_blessed_eval_loss",
    "held-back-slice loss of the currently blessed params — the bar "
    "a round's candidate must stay within tolerance of")
_steps_g = REGISTRY.gauge(
    "online_blessed_step",
    "step number of the most recent blessed continual-training "
    "checkpoint (0 until the first bless)")


def spec_from_znn(path: str, *, lr: float = 0.05,
                  momentum: float = 0.9
                  ) -> tuple[ModelSpec, list, list]:
    """Warm start: read a served ``.znn`` fc chain back into a fused
    :class:`ModelSpec` + params (+ zero velocities).

    Covers the fc families (fc layers, optional trailing softmax —
    loss becomes softmax-CE; without one, MSE).  Kohonen heads are the
    other online mode (:mod:`znicz_tpu.online.som`); conv chains stay
    offline-trained for now and raise here.
    """
    layers = export_mod.read_znn(path)
    softmax_head = bool(layers) and layers[-1].kind == "softmax"
    chain = layers[:-1] if softmax_head else layers
    if not chain or any(lay.kind != "fc" for lay in chain):
        kinds = [lay.kind for lay in layers]
        raise ValueError(
            f"online fine-tune covers fc chains (optional softmax "
            f"head); {path!r} is {kinds} — kohonen heads train via "
            f"online.som, everything else stays offline")
    spec_layers, params, vels = [], [], []
    for lay in chain:
        w = np.asarray(lay.w, np.float32)
        b = (np.asarray(lay.b, np.float32)
             if lay.b is not None else None)
        spec_layers.append(LayerSpec(
            kind="fc", activation=lay.activation,
            include_bias=b is not None,
            hypers=(lr, 0.0, 0.0, momentum),
            hypers_bias=(lr, 0.0, 0.0, momentum)))
        params.append((w, b))
        vels.append((np.zeros_like(w),
                     np.zeros_like(b) if b is not None else None))
    spec = ModelSpec(tuple(spec_layers),
                     loss="softmax" if softmax_head else "mse")
    return spec, params, vels


def export_fc_znn(spec: ModelSpec, params, path: str, *,
                  commit: bool = True) -> str:
    """Write fc params back to the ``.znn`` container (the exact
    inverse of :func:`spec_from_znn`).  ``commit=True`` takes the
    atomic publish path (tmp + rename + manifest — what a candidates
    directory wants); ``commit=False`` writes raw bytes at ``path``
    (what :meth:`CheckpointSource.materialize`'s tmp contract wants —
    the promotion controller owns the commit there)."""
    target = path + ".tmp" if commit else path
    n = len(spec.layers) + (1 if spec.loss == "softmax" else 0)
    with open(target, "wb") as fh:
        _write_header(fh, n)
        for lay, (w, b) in zip(spec.layers, params):
            w = np.asarray(w, np.float32)
            bb = None if b is None else np.asarray(b, np.float32)
            _pack_layer(fh, KIND["fc"], ACT[lay.activation],
                        [w.shape[0], w.shape[1]], w, bb)
        if spec.loss == "softmax":
            _pack_layer(fh, KIND["softmax"], 0, [])
    return _commit_znn(path) if commit else path


class OnlineTrainer:
    """Bounded-round continual fine-tuning of an fc ``.znn`` on
    replayed capture traffic (see the module docstring for the round
    lifecycle)."""

    def __init__(self, model_path: str, capture_dir: str, *,
                 candidates_dir: str | None = None,
                 checkpoint_dir: str | None = None,
                 lr: float = 0.05, momentum: float = 0.9,
                 batch: int = 16, round_samples: int = 128,
                 min_round_samples: int = 32,
                 epochs_per_round: int = 2,
                 holdback_every: int = 8, eval_max: int = 256,
                 tol: float = 0.10, abs_tol: float = 1e-4,
                 seed: int = 0, poll_timeout_s: float = 5.0,
                 model: str | None = None, window: int = 4096):
        if candidates_dir is None and checkpoint_dir is None:
            raise ValueError("pass candidates_dir and/or "
                             "checkpoint_dir — a trainer whose blessed "
                             "rounds go nowhere closes no loop")
        if holdback_every < 2:
            raise ValueError(f"holdback_every must be >= 2, got "
                             f"{holdback_every}")
        self.model_path = os.fspath(model_path)
        self.spec, params, vels = spec_from_znn(self.model_path, lr=lr,
                                                momentum=momentum)
        self.trainer = FusedTrainer(spec=self.spec, params=params,
                                    vels=vels)
        self.reader = ReplayReader(capture_dir, seed=seed,
                                   window=window, model=model)
        self.candidates_dir = (os.path.abspath(candidates_dir)
                               if candidates_dir else None)
        if self.candidates_dir:
            os.makedirs(self.candidates_dir, exist_ok=True)
        self.checkpoint_dir = (os.path.abspath(checkpoint_dir)
                               if checkpoint_dir else None)
        self._checkpointer = None
        self.batch = int(batch)
        self.round_samples = int(round_samples)
        self.min_round_samples = max(int(min_round_samples),
                                     holdback_every)
        self.epochs_per_round = int(epochs_per_round)
        self.holdback_every = int(holdback_every)
        self.eval_max = int(eval_max)
        self.tol = float(tol)
        self.abs_tol = float(abs_tol)
        self.poll_timeout_s = float(poll_timeout_s)
        self._rng = np.random.default_rng(seed)
        #: the held-back slice (never trained on), capped FIFO
        self._eval_x = np.zeros((0, 0), np.float32)
        self._eval_t = np.zeros((0,), np.int32)
        #: host snapshot of the blessed params/vels — the revert
        #: target for refused rounds and the bar for blessing
        self._blessed = self._host_state()
        self.step = 0
        self.rounds = {"blessed": 0, "refused": 0, "starved": 0}
        self.last_outcome: str | None = None
        self.last_eval: float | None = None
        self.last_blessed_eval: float | None = None

    # -- helpers -----------------------------------------------------------
    def _host_state(self):
        snap = []
        for (w, b), (vw, vb) in zip(self.trainer.params,
                                    self.trainer.vels):
            snap.append(((np.asarray(w).copy(),
                          np.asarray(b).copy() if b is not None
                          else None),
                         (np.asarray(vw).copy(),
                          np.asarray(vb).copy() if vb is not None
                          else None)))
        return snap

    def _restore_state(self, snap) -> None:
        import jax
        self.trainer.params = jax.device_put(
            [p for p, _v in snap])
        self.trainer.vels = jax.device_put(
            [v for _p, v in snap])

    def _eval_loss(self) -> float | None:
        """Masked-mean loss of the CURRENT trainer params on the
        held-back slice (None while the slice is empty).  The slice is
        evaluated as one padded step of ``eval_max`` rows, so its
        growth never recompiles the eval executable."""
        n = len(self._eval_x)
        if n == 0:
            return None
        # fixed-shape eval: the slice grows every round, and a jit
        # keyed on the raw array shape would recompile per growth —
        # pad the DATA to eval_max rows once and let the index/mask
        # machinery ignore the tail (one executable for the trainer's
        # whole lifetime)
        pad = self.eval_max - n
        x = np.concatenate([self._eval_x,
                            np.zeros((pad,) + self._eval_x.shape[1:],
                                     np.float32)]) if pad > 0 \
            else self._eval_x
        t = np.concatenate([self._eval_t,
                            np.zeros((pad,) + self._eval_t.shape[1:],
                                     self._eval_t.dtype)]) if pad > 0 \
            else self._eval_t
        m = self.trainer.eval_epoch(x, t, np.arange(n), self.eval_max)
        return float(np.asarray(m["loss"]).mean())

    def _labels_for(self, y: np.ndarray) -> np.ndarray:
        if self.spec.loss == "softmax":
            return np.argmax(y, axis=1).astype(np.int32)
        return y.astype(np.float32)

    def _checkpoint(self, step: int) -> str | None:
        if self.checkpoint_dir is None:
            return None
        if self._checkpointer is None:
            from ..parallel.checkpoint import TrainerCheckpointer
            self._checkpointer = TrainerCheckpointer(
                self.checkpoint_dir, max_to_keep=5)
        self._checkpointer.save(self.trainer, step, block=True)
        return os.path.join(self.checkpoint_dir, str(step))

    def checkpoint_exporter(self, step_dir: str, tmp_path: str) -> None:
        """The ``CheckpointSource(exporter=...)`` hook: restore one
        blessed step into a scratch trainer and write its fc chain as
        raw ``.znn`` bytes at ``tmp_path`` (the controller owns the
        atomic commit + manifest around it).  The scratch trainer
        reuses ``self.spec`` — the checkpoint's spec fingerprint pins
        layer kinds AND hypers, so a fresh ``spec_from_znn`` with
        different lr would refuse to restore."""
        from ..parallel.checkpoint import restore_trainer
        params = [(w.copy(), None if b is None else b.copy())
                  for (w, b), _v in self._blessed]
        vels = [(vw.copy(), None if vb is None else vb.copy())
                for _p, (vw, vb) in self._blessed]
        scratch = FusedTrainer(spec=self.spec, params=params,
                               vels=vels)
        restore_trainer(scratch, os.path.dirname(step_dir),
                        step=int(os.path.basename(step_dir)))
        export_fc_znn(scratch.spec, scratch.params, tmp_path,
                      commit=False)

    # -- one round ---------------------------------------------------------
    def run_round(self, *, poison_labels: bool = False) -> dict:
        """Gather → train → judge → bless/refuse (module docstring).
        ``poison_labels`` is the chaos drill's hook: it trains the
        round on shuffled labels at an exploded learning rate — a
        genuinely regressed candidate the blessing MUST refuse."""
        records = self.reader.take(self.round_samples,
                                   timeout_s=self.poll_timeout_s)
        if len(records) < self.min_round_samples:
            # honest degradation: a cold log trains nothing and blocks
            # nothing — the round reports starved and the caller
            # decides how long to wait for traffic
            self.rounds["starved"] += 1
            self.last_outcome = "starved"
            _rounds.inc(outcome="starved")
            return {"outcome": "starved", "gathered": len(records),
                    "needed": self.min_round_samples}
        x, y = records_to_arrays(records)
        t = self._labels_for(y)
        # the held-back slice: every holdback_every-th gathered row is
        # NEVER trained on; FIFO-capped so eval stays one padded step
        hold = np.zeros(len(x), bool)
        hold[::self.holdback_every] = True
        self._extend_eval(x[hold], t[hold])
        tx, tt = x[~hold], t[~hold]
        blessed_loss = self._judged_blessed_loss()
        lr_scale = 1.0
        if poison_labels:
            tt = tt.copy()
            self._rng.shuffle(tt)
            lr_scale = 50.0
        # fixed-capacity train arrays, for the same no-recompile
        # reason as the eval pad: only the index list (and therefore
        # the scan length, snapped to whole batches) varies round to
        # round, so the executable count stays bounded instead of
        # "one per distinct gather".  Multi-row requests make one
        # RECORD expand to many rows, so n_tr can exceed
        # round_samples — quantize the capacity up to the next
        # round_samples multiple rather than letting every row count
        # mint a fresh padded shape (and a fresh compile)
        n_tr = len(tx)
        cap = self.round_samples * max(
            1, -(-n_tr // self.round_samples))
        if n_tr < cap:
            tx = np.concatenate([tx, np.zeros(
                (cap - n_tr,) + tx.shape[1:], np.float32)])
            tt = np.concatenate([tt, np.zeros(
                (cap - n_tr,) + tt.shape[1:], tt.dtype)])
        for _ in range(self.epochs_per_round):
            self.trainer.train_epoch(tx, tt, np.arange(n_tr),
                                     self.batch, sync=True,
                                     lr_scale=lr_scale)
        cand_loss = self._eval_loss()
        self.last_eval = cand_loss
        if cand_loss is not None:
            _eval_g.set(cand_loss)
        refused_why = None
        if cand_loss is None:
            refused_why = "no held-back slice to judge against"
        elif not np.isfinite(cand_loss):
            refused_why = f"non-finite candidate eval ({cand_loss})"
        elif blessed_loss is not None and cand_loss \
                > blessed_loss * (1.0 + self.tol) + self.abs_tol:
            refused_why = (f"held-back eval regressed: "
                           f"{cand_loss:.6f} vs blessed "
                           f"{blessed_loss:.6f} (tol {self.tol:g})")
        if refused_why is not None:
            self._restore_state(self._blessed)
            self.rounds["refused"] += 1
            self.last_outcome = "refused"
            _rounds.inc(outcome="refused")
            log.warning("round refused: %s", refused_why)
            return {"outcome": "refused", "why": refused_why,
                    "eval_loss": cand_loss,
                    "blessed_loss": blessed_loss,
                    "trained": int(n_tr)}
        # blessed: snapshot, checkpoint (manifest = the bless mark),
        # export the candidate for the promotion watcher
        self._blessed = self._host_state()
        self.last_blessed_eval = cand_loss
        _blessed_g.set(cand_loss)
        self.step += 1
        _steps_g.set(self.step)
        step_dir = self._checkpoint(self.step)
        candidate = None
        if self.candidates_dir is not None:
            candidate = os.path.join(self.candidates_dir,
                                     f"online-{self.step:06d}.znn")
            export_fc_znn(self.spec, self.trainer.params, candidate,
                          commit=True)
        self.rounds["blessed"] += 1
        self.last_outcome = "blessed"
        _rounds.inc(outcome="blessed")
        return {"outcome": "blessed", "step": self.step,
                "eval_loss": cand_loss, "blessed_loss": blessed_loss,
                "trained": int(n_tr), "candidate": candidate,
                "checkpoint": step_dir}

    def _extend_eval(self, x: np.ndarray, t: np.ndarray) -> None:
        if len(x) == 0:
            return
        if self._eval_x.size == 0:
            self._eval_x, self._eval_t = x, t
        else:
            self._eval_x = np.concatenate([self._eval_x, x])
            self._eval_t = np.concatenate([self._eval_t, t])
        if len(self._eval_x) > self.eval_max:
            self._eval_x = self._eval_x[-self.eval_max:]
            self._eval_t = self._eval_t[-self.eval_max:]

    def _judged_blessed_loss(self) -> float | None:
        """The blessed params' loss on the CURRENT held-back slice —
        re-measured each round (the slice grows), on the snapshot, so
        candidate and incumbent race on identical rows."""
        if len(self._eval_x) == 0:
            return None
        live = self._host_state()
        self._restore_state(self._blessed)
        try:
            loss = self._eval_loss()
        finally:
            self._restore_state(live)
        if loss is not None:
            self.last_blessed_eval = loss
            _blessed_g.set(loss)
        return loss

    # -- introspection / lifecycle ----------------------------------------
    def status(self) -> dict:
        return {"step": self.step, "rounds": dict(self.rounds),
                "last_outcome": self.last_outcome,
                "last_eval_loss": self.last_eval,
                "blessed_eval_loss": self.last_blessed_eval,
                "eval_rows": int(len(self._eval_x)),
                "replay": self.reader.status()}

    def close(self) -> None:
        if self._checkpointer is not None:
            self._checkpointer.close()
