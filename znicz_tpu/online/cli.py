"""``python -m znicz_tpu online-train`` — the continual trainer as a
sidecar process.

Pair it with a capturing server and the stock promotion watcher to
close the whole loop with three processes and zero custom code::

    python -m znicz_tpu serve --model m.znn --capture-dir cap \\
        --port 8101
    python -m znicz_tpu online-train --model m.znn \\
        --capture-dir cap --candidates cands
    python -m znicz_tpu promote --candidates cands \\
        --url http://127.0.0.1:8101/        # (--fleet for a router)

The model family is auto-detected from the ``.znn``: an fc chain takes
the gradient fine-tune path (:class:`~znicz_tpu.online.trainer.
OnlineTrainer`), a kohonen head takes the SOM online mode
(:class:`~znicz_tpu.online.som.OnlineSom`).  Exit codes: 0 clean stop,
2 when ``--rounds`` were requested but every round starved (no
traffic ever became replayable — the operator wired the wrong
capture dir, or the tap is off).
"""

from __future__ import annotations

import json
import signal
import sys
import threading


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="znicz_tpu online-train",
        description="continual trainer: fine-tune a served model on "
                    "replayed capture-log traffic in bounded rounds, "
                    "bless/refuse each round on a held-back slice, "
                    "export blessed candidates for the promotion "
                    "watcher (docs/online.md)")
    p.add_argument("--model", required=True,
                   help="warm-start .znn — the artifact the fleet is "
                        "serving (fc chain or kohonen head; the "
                        "family picks the training mode)")
    p.add_argument("--capture-dir", required=True,
                   help="the serving tap's segment ring "
                        "(serve --capture-dir)")
    p.add_argument("--candidates", default=None,
                   help="directory blessed rounds export candidate "
                        ".znn files into (what `promote "
                        "--candidates` watches)")
    p.add_argument("--checkpoints", default=None,
                   help="TrainerCheckpointer directory for blessed "
                        "steps (durability manifest = the bless "
                        "mark; what promotion.CheckpointSource "
                        "watches) — fc mode only")
    p.add_argument("--capture-model", default=None, metavar="NAME",
                   help="replay only records captured for this zoo "
                        "model name (default: everything)")
    p.add_argument("--rounds", type=int, default=0,
                   help="run this many non-starved rounds then exit "
                        "(0 = run until SIGINT/SIGTERM)")
    p.add_argument("--round-samples", type=int, default=128,
                   help="replayed records gathered per round (the "
                        "bounded round size)")
    p.add_argument("--min-round-samples", type=int, default=32,
                   help="fewer gathered than this = a starved round: "
                        "no training, no blocking")
    p.add_argument("--poll-timeout-s", type=float, default=5.0,
                   help="bounded wait for the round's gather before "
                        "degrading to starved")
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--epochs-per-round", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.05,
                   help="fc mode fine-tune learning rate")
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--som-lr", type=float, default=0.3,
                   help="kohonen mode: lr0 of the exponential decay "
                        "schedule (rounds stand in for epochs)")
    p.add_argument("--holdback-every", type=int, default=8,
                   help="every Nth gathered record joins the "
                        "held-back slice the bless judgment runs on "
                        "(never trained)")
    p.add_argument("--tol", type=float, default=0.10,
                   help="bless tolerance: candidate held-back loss "
                        "(fc) / quantization error (SOM) may not "
                        "exceed blessed x (1 + tol)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--window", type=int, default=4096,
                   help="replay window: pending records retained "
                        "between rounds (oldest dropped beyond it)")
    p.add_argument("--idle-wait-s", type=float, default=2.0,
                   help="sleep between rounds when the last one "
                        "starved (run-forever mode)")
    p.add_argument("--max-starved", type=int, default=5,
                   help="with --rounds set: consecutive starved "
                        "rounds before giving up with exit code 2 "
                        "(a bounded run against a dead tap must not "
                        "hang; run-forever mode waits indefinitely)")
    p.add_argument("--fault-plan", default=None,
                   help="chaos: install a fault plan (inline JSON or "
                        "@file; see znicz_tpu.resilience.faults)")
    args = p.parse_args(argv)
    if args.fault_plan is not None:
        from ..resilience import faults as _faults
        _faults.install(_faults.parse_plan(args.fault_plan))
    from ..export import read_znn
    kinds = [lay.kind for lay in read_znn(args.model)]
    som_mode = kinds == ["kohonen"]
    if som_mode:
        if not args.candidates:
            p.error("kohonen mode needs --candidates (it has no "
                    "checkpointer tier)")
        from .som import OnlineSom
        worker = OnlineSom(
            args.model, args.capture_dir,
            candidates_dir=args.candidates,
            learning_rate=args.som_lr,
            round_samples=args.round_samples,
            min_round_samples=args.min_round_samples,
            holdback_every=args.holdback_every, tol=args.tol,
            seed=args.seed, poll_timeout_s=args.poll_timeout_s,
            model=args.capture_model, window=args.window)
    else:
        if not args.candidates and not args.checkpoints:
            p.error("pass --candidates and/or --checkpoints")
        from .trainer import OnlineTrainer
        worker = OnlineTrainer(
            args.model, args.capture_dir,
            candidates_dir=args.candidates,
            checkpoint_dir=args.checkpoints,
            lr=args.lr, momentum=args.momentum, batch=args.batch,
            round_samples=args.round_samples,
            min_round_samples=args.min_round_samples,
            epochs_per_round=args.epochs_per_round,
            holdback_every=args.holdback_every, tol=args.tol,
            seed=args.seed, poll_timeout_s=args.poll_timeout_s,
            model=args.capture_model, window=args.window)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    mode = "kohonen-online" if som_mode else "fc-fine-tune"
    print(f"online-train [{mode}]: {args.model} <- replay of "
          f"{args.capture_dir} -> candidates "
          f"{args.candidates or '-'} / checkpoints "
          f"{args.checkpoints or '-'}", flush=True)
    done = 0
    starved_streak = 0
    try:
        while not stop.is_set():
            out = worker.run_round()
            print(json.dumps({"round": worker.status()["rounds"],
                              **out}), flush=True)
            if out["outcome"] != "starved":
                done += 1
                starved_streak = 0
                if args.rounds and done >= args.rounds:
                    break
            else:
                starved_streak += 1
                if args.rounds and starved_streak >= args.max_starved:
                    # a bounded run against a tap that never fills:
                    # give up loudly instead of hanging (exit 2 below)
                    break
                stop.wait(args.idle_wait_s)
    finally:
        closer = getattr(worker, "close", None)
        if closer is not None:
            closer()
    print(json.dumps({"final": worker.status()}), flush=True)
    if args.rounds and done == 0:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
