"""Traffic capture: a bounded, sampled, fsync'd replay log of /predict.

The paper's lineage made *serving traffic* the training feed — VELES's
master–slave topology existed to stream data into training
(``apply_data_from_slave`` aggregation, PAPER.md), and its Kohonen
units are explicitly online learners.  This module is the serving-side
half of that loop: a **tap** on the request path that appends every
served ``(input tensor, chosen outputs)`` pair to an append-only log
the continual trainer (:mod:`znicz_tpu.online.trainer`) replays.

Design constraints, in priority order:

1. **Fail-open.**  The tap rides the request path: a full disk, a slow
   fsync, a log-roll error — or the injected ``capture.append`` chaos
   fault — must never fail or delay a ``/predict`` answer.  ``append``
   only enqueues into a bounded in-memory ring and swallows every
   exception (counted in ``capture_dropped_total{reason}``); all file
   I/O happens on one background writer thread.
2. **Bounded.**  The log is a byte-budgeted ring of segment files
   (``seg-<n>.zcap``): when the retained bytes exceed ``max_bytes``
   the oldest *closed* segments are deleted.  The in-memory queue is
   bounded too — a stalled disk drops records (``reason=backlog``),
   it does not grow the heap.
3. **Durable enough to replay.**  The writer fsyncs after every write
   batch and on every segment roll, so a crashed serving process loses
   at most the last in-flight batch; the record framing (length +
   crc32) lets the replay tailer detect and tolerate a torn tail.
4. **Sampled.**  ``sample < 1.0`` keeps a seeded fraction of served
   answers (``reason=sampled`` counts the rest) — heavy fleets don't
   need every request to fine-tune on.

Record framing (one segment = a run of records)::

    magic   b"ZCR1"             4 bytes
    u32     payload length
    u32     crc32(payload)
    payload:
        u8   model-name length, name bytes (utf-8; 0 = single-model)
        u32  x length,  x as a serving.wire binary tensor
        u32  y length,  y as a serving.wire binary tensor

Tensors reuse the PR 13 wire format (:mod:`znicz_tpu.serving.wire`) —
one encoder/decoder for the HTTP hot path and the replay log.
"""

from __future__ import annotations

import collections
import os
import random
import struct
import threading
import time
import zlib

import numpy as np

from ..resilience import faults
from ..serving import wire
from ..telemetry.registry import REGISTRY

#: record framing header: magic, payload length, crc32(payload)
REC_HEADER = struct.Struct("<4sII")
REC_MAGIC = b"ZCR1"
SEGMENT_PREFIX = "seg-"
SEGMENT_SUFFIX = ".zcap"

_records = REGISTRY.counter(
    "capture_records_total",
    "served /predict (input, outputs) pairs committed to the traffic "
    "capture log (after sampling; the continual trainer's feed)")
_dropped = REGISTRY.counter(
    "capture_dropped_total",
    "served answers NOT captured, by reason (sampled = the --capture-"
    "sample coin | backlog = the bounded writer queue was full | "
    "error = an append/write/roll/fsync failure, incl. the injected "
    "capture.append fault | closed = tap already shut down) — the tap "
    "is fail-open, so every drop lands here instead of in a client's "
    "answer")
_bytes_g = REGISTRY.gauge(
    "capture_bytes",
    "bytes currently retained across the capture log's segment files "
    "(the ring deletes the oldest closed segments past --capture-mb)")
_segments_g = REGISTRY.gauge(
    "capture_segments",
    "segment files currently retained in the capture log ring")


class CaptureRecord:
    """One replayable traffic sample."""

    __slots__ = ("model", "x", "y")

    def __init__(self, model: str | None, x: np.ndarray, y: np.ndarray):
        self.model = model
        self.x = x
        self.y = y


def encode_record(model: str | None, x: np.ndarray,
                  y: np.ndarray) -> bytes:
    """One framed record: header + (name, x-wire, y-wire) payload."""
    name = (model or "").encode("utf-8")
    if len(name) > 255:
        raise ValueError(f"model name too long for the record frame "
                         f"({len(name)} bytes)")
    xb = wire.encode_tensor(np.ascontiguousarray(x, np.float32))
    yb = wire.encode_tensor(np.ascontiguousarray(y, np.float32))
    payload = (struct.pack("<B", len(name)) + name
               + struct.pack("<I", len(xb)) + xb
               + struct.pack("<I", len(yb)) + yb)
    return REC_HEADER.pack(REC_MAGIC, len(payload),
                           zlib.crc32(payload)) + payload


def decode_payload(payload: bytes) -> CaptureRecord:
    (nlen,) = struct.unpack_from("<B", payload, 0)
    off = 1
    name = payload[off:off + nlen].decode("utf-8") or None
    off += nlen
    (xlen,) = struct.unpack_from("<I", payload, off)
    off += 4
    x = wire.decode_tensor(payload[off:off + xlen])
    off += xlen
    (ylen,) = struct.unpack_from("<I", payload, off)
    off += 4
    y = wire.decode_tensor(payload[off:off + ylen])
    return CaptureRecord(name, x, y)


def segment_files(directory: str) -> list[str]:
    """Retained segment paths, oldest first (names sort by sequence)."""
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith(SEGMENT_PREFIX)
                       and n.endswith(SEGMENT_SUFFIX))
    except FileNotFoundError:
        return []
    return [os.path.join(directory, n) for n in names]


def read_records(path: str, offset: int = 0):
    """Parse complete records from ``path`` starting at ``offset``.

    Returns ``(records, new_offset, status)`` where status is

    * ``"ok"`` — the segment parsed cleanly to its end;
    * ``"partial"`` — an incomplete record at the tail (a writer may
      still be mid-append; retry from ``new_offset`` later);
    * ``"torn"`` — a crc/magic mismatch at ``new_offset``: the bytes
      from there on are unusable (a crashed writer's torn tail — the
      length field itself may be garbage, so skipping past it is not
      safe).

    The replay tailer maps these onto its degradation policy; this
    function never raises for content problems (an unreadable FILE
    still raises OSError — the caller owns that policy).
    """
    with open(path, "rb") as fh:
        fh.seek(offset)
        blob = fh.read()
    records: list[CaptureRecord] = []
    pos = 0
    n = len(blob)
    while True:
        if pos + REC_HEADER.size > n:
            status = "ok" if pos == n else "partial"
            return records, offset + pos, status
        magic, plen, crc = REC_HEADER.unpack_from(blob, pos)
        if magic != REC_MAGIC:
            return records, offset + pos, "torn"
        end = pos + REC_HEADER.size + plen
        if end > n:
            return records, offset + pos, "partial"
        payload = blob[pos + REC_HEADER.size:end]
        if zlib.crc32(payload) != crc:
            return records, offset + pos, "torn"
        try:
            records.append(decode_payload(payload))
        except Exception:
            # a record that framed cleanly but decodes rotten: skip it
            # alone (the frame told us exactly where the next starts)
            pass
        pos = end


class CaptureLog:
    """The serving tap: bounded queue in front of one writer thread.

    ``append`` is the only request-path call and it cannot raise or
    block on I/O; everything else (encode, write, fsync, roll, ring
    trim) happens on the ``znicz-capture-writer`` thread.
    """

    def __init__(self, directory: str, *, max_bytes: int = 64_000_000,
                 segment_bytes: int | None = None, sample: float = 1.0,
                 seed: int = 0, queue_depth: int = 512,
                 flush_interval_s: float = 0.2):
        if not 0.0 < sample <= 1.0:
            raise ValueError(f"sample must be in (0, 1], got {sample}")
        if int(max_bytes) < 4096:
            raise ValueError(f"max_bytes must be >= 4096, got "
                             f"{max_bytes}")
        self.directory = os.path.abspath(os.fspath(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.max_bytes = int(max_bytes)
        #: segments roll well under the budget so the ring always has
        #: closed segments to delete — a single giant open segment
        #: could never be trimmed
        self.segment_bytes = int(segment_bytes) if segment_bytes \
            else max(4096, self.max_bytes // 8)
        self.sample = float(sample)
        self.queue_depth = int(queue_depth)
        self.flush_interval_s = float(flush_interval_s)
        self._lock = threading.Lock()
        self._q: collections.deque = collections.deque()
        self._inflight = 0
        self._stats = collections.Counter()
        self._rng = random.Random(seed)
        self._closed = False
        # writer-thread-only file state (never touched under _lock —
        # the writer owns it; metrics() reads the two scalars lock-free
        # as a deliberately racy-but-benign snapshot)
        self._fh = None
        self._seg_seq = 0
        self._seg_open_bytes = 0
        self._retained: list = []         # [(path, bytes)] closed segs
        self._retained_bytes = 0
        self._adopt_existing()
        self._wake = threading.Event()
        self._done = threading.Event()
        self._writer = threading.Thread(target=self._writer_loop,
                                        daemon=True,
                                        name="znicz-capture-writer")
        self._writer.start()

    # -- request path ------------------------------------------------------
    def append(self, x, y, model: str | None = None) -> bool:
        """Enqueue one served sample.  Fail-open: never raises, never
        does file I/O; a False return means the sample was dropped
        (sampled out, queue full, tap closed, or an injected/real
        failure) and counted in ``capture_dropped_total``."""
        try:
            faults.inject("capture.append")
            with self._lock:
                if self._closed:
                    self._stats["dropped_closed"] += 1
                    reason = "closed"
                elif self.sample < 1.0 \
                        and self._rng.random() >= self.sample:
                    self._stats["dropped_sampled"] += 1
                    reason = "sampled"
                elif len(self._q) >= self.queue_depth:
                    self._stats["dropped_backlog"] += 1
                    reason = "backlog"
                else:
                    self._q.append((model, x, y))
                    reason = None
            if reason is None:
                self._wake.set()
                return True
            _dropped.inc(reason=reason)
            return False
        except Exception:
            # the fail-open contract: ANY failure here (including the
            # capture.append chaos fault) is a dropped sample, never a
            # failed or delayed answer
            try:
                with self._lock:
                    self._stats["dropped_error"] += 1
                _dropped.inc(reason="error")
            except Exception:
                pass
            return False

    # -- writer thread -----------------------------------------------------
    def _writer_loop(self) -> None:
        while True:
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            batch = self._drain()
            if batch:
                self._write_batch(batch)
            with self._lock:
                closed = self._closed and not self._q
            if closed:
                break
        self._close_segment()
        self._done.set()

    def _drain(self) -> list:
        with self._lock:
            batch = list(self._q)
            self._q.clear()
            self._inflight = len(batch)
        return batch

    def _write_batch(self, batch: list) -> None:
        wrote = 0
        for model, x, y in batch:
            try:
                blob = encode_record(model, x, y)
                if self._fh is not None \
                        and self._seg_open_bytes + len(blob) \
                        > self.segment_bytes:
                    self._close_segment()
                if self._fh is None:
                    self._open_segment()
                self._fh.write(blob)
                self._seg_open_bytes += len(blob)
                wrote += 1
            except Exception:
                with self._lock:
                    self._stats["dropped_error"] += 1
                _dropped.inc(reason="error")
        if wrote:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except Exception:
                # durability degraded, service intact: the records are
                # in the page cache at worst — count, keep serving
                with self._lock:
                    self._stats["fsync_errors"] += 1
            with self._lock:
                self._stats["records"] += wrote
            _records.inc(wrote)
        self._trim_ring()
        self._publish_gauges()
        with self._lock:
            self._inflight = 0

    def _adopt_existing(self) -> None:
        """A restarted server appends AFTER the existing ring instead
        of clobbering it — the replay log outlives one process."""
        for path in segment_files(self.directory):
            try:
                nbytes = os.path.getsize(path)
            except OSError:
                continue
            name = os.path.basename(path)
            try:
                seq = int(name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])
            except ValueError:
                continue
            self._seg_seq = max(self._seg_seq, seq + 1)
            self._retained.append((path, nbytes))
            self._retained_bytes += nbytes

    def _open_segment(self) -> None:
        path = os.path.join(
            self.directory,
            f"{SEGMENT_PREFIX}{self._seg_seq:08d}{SEGMENT_SUFFIX}")
        self._seg_seq += 1
        self._fh = open(path, "ab")
        self._seg_path = path
        self._seg_open_bytes = 0

    def _close_segment(self) -> None:
        if self._fh is None:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
        except Exception:
            with self._lock:
                self._stats["fsync_errors"] += 1
        self._retained.append((self._seg_path, self._seg_open_bytes))
        self._retained_bytes += self._seg_open_bytes
        self._fh = None
        self._seg_open_bytes = 0

    def _trim_ring(self) -> None:
        """Delete oldest closed segments until retained + open bytes
        fit the budget."""
        while self._retained and (self._retained_bytes
                                  + self._seg_open_bytes
                                  > self.max_bytes):
            path, nbytes = self._retained.pop(0)
            try:
                os.unlink(path)
            except OSError:
                pass
            self._retained_bytes -= nbytes
            with self._lock:
                self._stats["segments_deleted"] += 1

    def _publish_gauges(self) -> None:
        _bytes_g.set(self._retained_bytes + self._seg_open_bytes)
        _segments_g.set(len(self._retained)
                        + (1 if self._fh is not None else 0))

    # -- introspection / lifecycle ----------------------------------------
    def metrics(self) -> dict:
        with self._lock:
            stats = dict(self._stats)
            queued = len(self._q)
        return {"directory": self.directory,
                "records": stats.get("records", 0),
                "queued": queued,
                "dropped_sampled": stats.get("dropped_sampled", 0),
                "dropped_backlog": stats.get("dropped_backlog", 0),
                "dropped_error": stats.get("dropped_error", 0),
                "dropped_closed": stats.get("dropped_closed", 0),
                "fsync_errors": stats.get("fsync_errors", 0),
                "segments_deleted": stats.get("segments_deleted", 0),
                # benign racy snapshot of writer-owned state: a scrape
                # mid-roll may be one record stale, never torn
                "bytes": self._retained_bytes + self._seg_open_bytes,
                "segments": len(self._retained)
                + (1 if self._fh is not None else 0),
                "max_bytes": self.max_bytes,
                "segment_bytes": self.segment_bytes,
                "sample": self.sample}

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block (bounded) until the queue has drained and the bytes
        are fsync'd — the tests' and the drill's barrier, not a
        request-path call."""
        deadline = time.monotonic() + timeout_s
        self._wake.set()
        while time.monotonic() < deadline:
            with self._lock:
                settled = not self._q and self._inflight == 0
            if settled:
                return True
            self._wake.set()
            time.sleep(0.01)
        return False

    def close(self, timeout_s: float = 10.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        self._done.wait(timeout_s)
        self._publish_gauges()
