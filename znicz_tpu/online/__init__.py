"""Online/continual training: the live-data loop (docs/online.md).

serve --capture-dir ──▶ capture ring ──▶ replay tailer ──▶ bounded
rounds (fc fine-tune or Kohonen online) ──▶ blessed checkpoints +
candidate ``.znn``s ──▶ the stock promotion controller ──▶ canary →
SLO watch → fleet rollout.  Every stage reuses a prior subsystem:
the PR 13 wire format frames the log, PR 6's sources/controller
consume the output, PR 14's fleet walk spreads it.
"""

from .capture import (CaptureLog, CaptureRecord, read_records,
                      segment_files)
from .replay import ReplayLoader, ReplayReader, records_to_arrays
from .som import OnlineSom, export_som_znn, read_som_znn
from .trainer import OnlineTrainer, export_fc_znn, spec_from_znn

__all__ = [
    "CaptureLog", "CaptureRecord", "read_records", "segment_files",
    "ReplayLoader", "ReplayReader", "records_to_arrays",
    "OnlineSom", "export_som_znn", "read_som_znn",
    "OnlineTrainer", "export_fc_znn", "spec_from_znn",
]
