"""Replay: tail the capture log into training batches.

The reader half of the live-data loop: :class:`ReplayReader` tails the
capture ring's segment files (:mod:`znicz_tpu.online.capture`),
shuffles within a bounded window under a seed, and **degrades
honestly** when the log is cold — a bounded poll returns what exists
(possibly nothing) instead of parking the trainer forever.

Torn-tail policy (the crash-consistency half of the capture format):

* an *incomplete* record at the tail of the **newest** segment is a
  writer that may still be mid-append — the reader holds its offset
  and retries on the next poll;
* an incomplete or crc-torn tail on a segment that is **no longer the
  newest** can never complete — it is counted
  (``replay_torn_records_total``) and the reader moves on;
* a crc mismatch anywhere stops consumption of that segment at the
  torn offset (the length field itself may be garbage — skipping past
  it is guessing).

Locks never span file I/O: the reader parses segments outside its
buffer lock and only takes the lock to splice parsed records in or
sample a batch out (the zlint lock/deadline rules patrol this module —
see ``znicz_tpu/analysis``).

:class:`ReplayLoader` adapts a snapshot of the log to the repo's
loader protocol (:class:`~znicz_tpu.loader.streaming.StreamingLoader`)
— train/validation ``class_lengths`` with every ``holdback_every``-th
record held back as the validation slice, labels derived from the
served outputs' argmax — so the unit-graph path can train from
captured traffic exactly like any other dataset.
"""

from __future__ import annotations

import os
import random
import threading
import time

import numpy as np

from ..loader.streaming import StreamingLoader
from ..telemetry.registry import REGISTRY
from . import capture as cap

_loaded = REGISTRY.counter(
    "replay_records_total",
    "capture-log records loaded by a replay tailer (complete, "
    "crc-verified frames handed to the continual trainer)")
_torn = REGISTRY.counter(
    "replay_torn_records_total",
    "unusable capture-log tails skipped by a replay tailer: a crc or "
    "framing mismatch, or an incomplete record on a segment the "
    "writer has already rolled past (crash debris, not data loss of "
    "the retained ring)")


class ReplayReader:
    """Single-consumer tailer over a capture directory.

    ``window`` bounds the pending-record buffer: when the trainer
    falls behind, the oldest unconsumed records are dropped (the point
    of replaying *live* traffic is recency, and an unbounded buffer
    would just be the queue-growth failure mode again).  Batches are
    drawn without replacement from the window by a seeded shuffle, so
    a fixed log + seed + call sequence replays bit-identically.
    """

    def __init__(self, directory: str, *, seed: int = 0,
                 window: int = 4096, model: str | None = None):
        self.directory = os.path.abspath(os.fspath(directory))
        self.window = int(window)
        self.model = model
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._buf: list[cap.CaptureRecord] = []
        #: per-segment consumed offset (path -> bytes); segments that
        #: disappeared from disk (ring-trimmed) are forgotten
        self._offsets: dict[str, int] = {}
        self._finished: set[str] = set()
        self.records_loaded = 0
        self.records_dropped = 0
        self.torn = 0

    # -- tailing -----------------------------------------------------------
    def poll(self) -> int:
        """Scan for new bytes once (no waiting): parse every readable
        new record into the window.  Returns how many records were
        loaded.  All file I/O happens lock-free; the buffer splice at
        the end is the only locked region."""
        segments = cap.segment_files(self.directory)
        live = set(segments)
        fresh: list[cap.CaptureRecord] = []
        torn = 0
        newest = segments[-1] if segments else None
        for path in segments:
            if path in self._finished:
                continue
            offset = self._offsets.get(path, 0)
            try:
                records, new_offset, status = cap.read_records(path,
                                                               offset)
            except OSError:
                continue                    # trimmed under us
            fresh.extend(records)
            self._offsets[path] = new_offset
            if status == "ok":
                if path != newest:
                    # fully consumed and the writer moved on: done
                    self._finished.add(path)
            elif status == "torn":
                torn += 1
                self._finished.add(path)
            elif status == "partial" and path != newest:
                # the writer rolled past a half-written tail — it will
                # never complete; count it and move on
                torn += 1
                self._finished.add(path)
        # forget state for ring-trimmed segments
        for path in list(self._offsets):
            if path not in live:
                self._offsets.pop(path, None)
                self._finished.discard(path)
        if self.model is not None:
            fresh = [r for r in fresh if r.model == self.model]
        with self._lock:
            self._buf.extend(fresh)
            overflow = len(self._buf) - self.window
            if overflow > 0:
                del self._buf[:overflow]
                self.records_dropped += overflow
            self.records_loaded += len(fresh)
            self.torn += torn
        if fresh:
            _loaded.inc(len(fresh))
        if torn:
            _torn.inc(torn)
        return len(fresh)

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    def take(self, n: int, *, timeout_s: float = 0.0,
             poll_interval_s: float = 0.05
             ) -> list[cap.CaptureRecord]:
        """Up to ``n`` records, drawn without replacement from the
        window by the seeded shuffle.  Polls the log until ``n`` are
        pending or ``timeout_s`` elapses, then returns **what exists**
        — an empty list on a cold log, never an unbounded block (the
        honest-degradation contract the trainer's ``starved`` outcome
        builds on)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        self.poll()
        while self.pending() < n and time.monotonic() < deadline:
            time.sleep(min(poll_interval_s,
                           max(0.0, deadline - time.monotonic())))
            self.poll()
        with self._lock:
            k = min(n, len(self._buf))
            if k == 0:
                return []
            picks = self._rng.sample(range(len(self._buf)), k)
            picks.sort()
            out = [self._buf[i] for i in picks]
            for i in reversed(picks):
                del self._buf[i]
            return out

    def status(self) -> dict:
        with self._lock:
            return {"pending": len(self._buf),
                    "loaded": self.records_loaded,
                    "dropped": self.records_dropped,
                    "torn": self.torn,
                    "window": self.window}


def records_to_arrays(records) -> tuple[np.ndarray, np.ndarray]:
    """Stack records into ``(x, y)`` float32 batches.  Multi-row
    requests contribute one row per sample; ragged feature widths (a
    mixed-model capture read without a ``model=`` filter) raise."""
    xs, ys = [], []
    for r in records:
        x = np.asarray(r.x, np.float32)
        y = np.asarray(r.y, np.float32)
        if x.ndim == 1:
            x = x[None]
        if y.ndim == 1:
            y = y[None]
        xs.append(x)
        ys.append(y)
    if not xs:
        return (np.zeros((0, 0), np.float32),
                np.zeros((0, 0), np.float32))
    return np.concatenate(xs), np.concatenate(ys)


class ReplayLoader(StreamingLoader):
    """Loader-protocol view of one capture-log snapshot.

    ``load_data`` materializes everything currently replayable: every
    ``holdback_every``-th row becomes the *validation* class (the
    held-back slice the blessing evaluation judges), the rest train;
    labels are the served outputs' argmax — the "chosen label" of
    self-training on one's own traffic.  ``refresh()`` re-polls the
    log and rebuilds the classes in place for the next epoch."""

    def __init__(self, directory: str, *, minibatch_size: int = 32,
                 holdback_every: int = 8, seed: int = 0,
                 model: str | None = None, window: int = 65536,
                 max_rows: int | None = None, **kwargs):
        super().__init__(None, "replay_loader",
                         minibatch_size=minibatch_size, **kwargs)
        if holdback_every < 2:
            raise ValueError(f"holdback_every must be >= 2 (1 would "
                             f"hold back EVERY row), got "
                             f"{holdback_every}")
        self.holdback_every = int(holdback_every)
        self.reader = ReplayReader(directory, seed=seed, model=model,
                                   window=window)
        #: backing-array row bound: every other stage of the loop is
        #: byte- or window-bounded, and a loader refreshed every epoch
        #: against a live ring must not concatenate toward OOM —
        #: oldest rows FIFO-trim past this (default: one window)
        self.max_rows = int(max_rows) if max_rows is not None \
            else int(window)
        self._data = np.zeros((0, 0), np.float32)
        self._labels = np.zeros((0,), np.int32)

    def refresh(self) -> int:
        """Pull everything newly replayable into the backing arrays;
        returns the number of rows added."""
        fresh = self.reader.take(self.reader.window, timeout_s=0.0)
        if not fresh:
            return 0
        x, y = records_to_arrays(fresh)
        labels = np.argmax(y, axis=1).astype(np.int32)
        if self._data.size == 0:
            self._data, self._labels = x, labels
        else:
            self._data = np.concatenate([self._data, x])
            self._labels = np.concatenate([self._labels, labels])
        if len(self._data) > self.max_rows:
            # FIFO trim (recency wins, same stance as the reader's
            # window).  The holdback pattern is positional, so a trim
            # can migrate a surviving row between classes — this
            # adapter feeds generic loader-protocol training, not the
            # OnlineTrainer's never-trained eval slice (that one keeps
            # its own FIFO-capped holdback)
            self._data = self._data[-self.max_rows:]
            self._labels = self._labels[-self.max_rows:]
        n = len(self._data)
        hold = np.zeros(n, bool)
        hold[::self.holdback_every] = True
        # base-class index space: test | validation | train
        self._valid_rows = np.flatnonzero(hold)
        self._train_rows = np.flatnonzero(~hold)
        self.class_lengths = [0, len(self._valid_rows),
                              len(self._train_rows)]
        return len(x)

    # -- StreamingLoader contract -----------------------------------------
    def load_meta(self) -> None:
        self.refresh()
        if not any(self.class_lengths):
            raise ValueError(
                f"capture log {self.reader.directory!r} holds no "
                f"replayable records yet (cold log) — retry after "
                f"traffic has flowed")
        self.sample_shape = tuple(self._data.shape[1:])
        self.raw_sample_shape = self.sample_shape
        self.label_dtype = np.int32

    def read_batch(self, indices) -> tuple[np.ndarray, np.ndarray]:
        rows = np.concatenate([self._valid_rows, self._train_rows])
        picked = rows[np.asarray(indices, np.int64)]
        return self._data[picked], self._labels[picked]
