"""Distributable protocol — per-unit hooks for distributed execution.

Capability parity with the reference's ``veles/distributable.py`` (mount
empty — surveyed contract, SURVEY.md §2.1): the master–slave job protocol
``generate_data_for_slave → apply_data_from_master → run →
generate_data_for_master → apply_data_from_slave``.

TPU-first redesign (SURVEY.md §2.4, the north star): the asynchronous
parameter-server star becomes synchronous SPMD data parallelism — gradient
aggregation (the reference's ``apply_data_from_slave`` fold) is a
``jax.lax.psum`` over the mesh's data axis inside the jitted step, riding
ICI.  The protocol methods are retained as the *sharding contract*: they
describe which state a unit owns globally (weights: replicated) vs
per-shard (minibatches: split), which is exactly what
``znicz_tpu.parallel`` needs to build shardings.  Units that carry no
distributed state inherit these no-ops.
"""

from __future__ import annotations


class Distributable:
    """Per-unit distributed-state contract (reference IDistributable)."""

    #: Does this unit need cross-replica negotiation at setup time?
    negotiates_on_connect = False

    def generate_data_for_slave(self, slave=None):
        """Master→slave payload (reference).  TPU mapping: the per-shard
        slice spec this unit consumes (e.g. loader minibatch indices)."""
        return None

    def apply_data_from_master(self, data) -> None:
        """Slave applies master payload (reference).  TPU mapping: install
        the shard slice before the step."""

    def generate_data_for_master(self):
        """Slave→master payload (reference: gradients/stats).  TPU mapping:
        the pytree this unit contributes to the cross-replica reduction."""
        return None

    def apply_data_from_slave(self, data, slave=None) -> None:
        """Master folds a slave's payload (reference: gradient aggregation
        point [baseline]).  TPU mapping: psum over the data axis — performed
        by the compiled step, not by this Python hook; kept for API parity
        and for host-side reductions of non-traced stats."""

    def drop_slave(self, slave=None) -> None:
        """Reference: master requeues a lost slave's job.  TPU mapping:
        slice failure → restart from checkpoint (SURVEY.md §5)."""
