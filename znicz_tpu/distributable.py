"""Distributable protocol — per-unit hooks for distributed execution.

Capability parity with the reference's ``veles/distributable.py`` (mount
empty — surveyed contract, SURVEY.md §2.1): the master–slave job protocol
``generate_data_for_slave → apply_data_from_master → run →
generate_data_for_master → apply_data_from_slave``.

TPU-first redesign (SURVEY.md §2.4, the north star): the asynchronous
parameter-server star becomes synchronous SPMD data parallelism, and the
surviving hooks are the **sharding contract**
:func:`znicz_tpu.parallel.distributed.distribute` consumes:

* ``generate_data_for_slave`` → ``{vector_name: (local_rows, total)}``
  — the per-shard arrays this unit owns on this process (loaders return
  their dataset shard; units with only replicated state return None);
* ``apply_data_from_master`` — install the globally batch-sharded
  jax.Arrays ``distribute`` assembled from every process's shard.

The gradient-fold pair (``generate_data_for_master`` /
``apply_data_from_slave``) is absorbed into the compiled step — the
reference's aggregation point is a ``jax.lax.psum`` over the mesh's data
axis riding ICI — so those hooks stay no-ops by design; ``drop_slave``
maps to restart-from-checkpoint
(:class:`znicz_tpu.parallel.distributed.CheckpointRecovery`).
"""

from __future__ import annotations


class Distributable:
    """Per-unit distributed-state contract (reference IDistributable)."""

    #: Does this unit need cross-replica negotiation at setup time?
    negotiates_on_connect = False

    def generate_data_for_slave(self, slave=None):
        """Per-shard payload: ``{vector_name: (local_rows, total_rows)}``
        of the arrays this unit owns that are SPLIT over the data axis,
        or None when the unit carries only replicated state.  Consumed
        by ``parallel.distributed.distribute`` (loaders implement it —
        ``loader.fullbatch.FullBatchLoader.generate_data_for_slave``)."""
        return None

    def apply_data_from_master(self, data) -> None:
        """Install the globally sharded arrays assembled from every
        process's ``generate_data_for_slave`` payload (loaders set their
        Vectors' devmem to the batch-sharded jax.Arrays)."""

    def generate_data_for_master(self):
        """Slave→master payload (reference: gradients/stats).  TPU
        mapping: absorbed — the pytree a unit contributes to the
        cross-replica reduction lives inside the jitted step (psum)."""
        return None

    def apply_data_from_slave(self, data, slave=None) -> None:
        """Master folds a slave's payload (reference: gradient aggregation
        point [baseline]).  TPU mapping: psum over the data axis — performed
        by the compiled step, not by this Python hook; kept for API parity
        and for host-side reductions of non-traced stats."""

    def drop_slave(self, slave=None) -> None:
        """Reference: master requeues a lost slave's job.  TPU mapping:
        slice failure → restart from checkpoint (SURVEY.md §5)."""
