"""Genetic hyperparameter search over the config tree.

Parity target: the reference ``veles/genetics/`` (mount empty — surveyed
contract, SURVEY.md §2.1 Genetics row: chromosome = config values,
fitness = workflow result; the genetics module mutated config leaves and
relaunched workflows).

TPU-first simplification: the reference forked whole launcher processes
per individual; here an evaluation is a plain callable (build + train a
workflow, return fitness), so populations can also be scored in-process
— on TPU the expensive part is the jitted training itself, and config
changes that keep shapes static reuse the compile cache across
individuals.  All randomness draws from the seeded PRNG streams."""

from __future__ import annotations

import dataclasses

import numpy as np

from . import prng
from .config import Config, root


@dataclasses.dataclass
class Gene:
    """One evolvable config leaf."""

    path: str                    # dotted path under the tree root
    lo: float
    hi: float
    is_int: bool = False

    def clip(self, v: float):
        v = float(np.clip(v, self.lo, self.hi))
        return int(round(v)) if self.is_int else v

    def sample(self, gen) -> float:
        return self.clip(gen.uniform(self.lo, self.hi))


@dataclasses.dataclass
class Individual:
    values: list
    fitness: float | None = None


class GeneticOptimizer:
    """Tournament-selection GA with blend crossover + gaussian mutation.

    ``evaluate(tree)`` receives a cloned config tree with the
    chromosome's values applied and returns a fitness (HIGHER is better —
    negate a loss).  The best tree is re-applied to the live ``root`` at
    the end (the reference applied the winning config the same way)."""

    def __init__(self, genes, evaluate, population_size=12,
                 generations=8, tournament=3, crossover_rate=0.7,
                 mutation_rate=0.15, mutation_sigma=0.2, elite=1,
                 tree: Config = root, stream="genetics"):
        self.genes = list(genes)
        self.evaluate = evaluate
        self.population_size = population_size
        self.generations = generations
        self.tournament = tournament
        self.crossover_rate = crossover_rate
        self.mutation_rate = mutation_rate
        self.mutation_sigma = mutation_sigma
        self.elite = elite
        self.tree = tree
        self.gen = prng.get(stream)
        self.history: list[dict] = []
        self.best: Individual | None = None

    # -- chromosome ↔ config ------------------------------------------------
    def apply(self, values, tree: Config) -> Config:
        for gene, v in zip(self.genes, values):
            tree.set_path(gene.path, v)
        return tree

    def _fitness(self, ind: Individual) -> float:
        if ind.fitness is None:
            tree = self.tree.clone()
            self.apply(ind.values, tree)
            ind.fitness = float(self.evaluate(tree))
        return ind.fitness

    # -- GA operators --------------------------------------------------------
    def _select(self, population) -> Individual:
        picks = [population[self.gen.randint(0, len(population))]
                 for _ in range(self.tournament)]
        return max(picks, key=lambda i: i.fitness)

    def _crossover(self, a: Individual, b: Individual) -> list:
        if self.gen.uniform(0, 1) > self.crossover_rate:
            return list(a.values)
        mix = self.gen.uniform(0, 1, len(self.genes))
        return [g.clip(m * va + (1 - m) * vb)
                for g, va, vb, m in zip(self.genes, a.values, b.values,
                                        mix)]

    def _mutate(self, values) -> list:
        out = []
        for g, v in zip(self.genes, values):
            if self.gen.uniform(0, 1) < self.mutation_rate:
                span = g.hi - g.lo
                v = g.clip(v + self.gen.normal(0.0,
                                               self.mutation_sigma * span))
            out.append(v)
        return out

    # -- main loop -----------------------------------------------------------
    def run(self) -> Individual:
        population = [Individual([g.sample(self.gen)
                                  for g in self.genes])
                      for _ in range(self.population_size)]
        for generation in range(self.generations):
            for ind in population:
                self._fitness(ind)
            population.sort(key=lambda i: -i.fitness)
            self.best = population[0]
            self.history.append({
                "generation": generation,
                "best_fitness": population[0].fitness,
                "best_values": list(population[0].values),
                "mean_fitness": float(np.mean(
                    [i.fitness for i in population]))})
            if generation == self.generations - 1:
                break
            nxt = [Individual(list(i.values), i.fitness)
                   for i in population[:self.elite]]
            while len(nxt) < self.population_size:
                child = self._crossover(self._select(population),
                                        self._select(population))
                nxt.append(Individual(self._mutate(child)))
            population = nxt
        self.apply(self.best.values, self.tree)   # install the winner
        return self.best
