"""Genetic hyperparameter search over the config tree.

Parity target: the reference ``veles/genetics/`` (mount empty — surveyed
contract, SURVEY.md §2.1 Genetics row: chromosome = config values,
fitness = workflow result; the genetics module mutated config leaves and
relaunched workflows).

TPU-first simplification: the reference forked whole launcher processes
per individual; here an evaluation is a plain callable (build + train a
workflow, return fitness), so populations can also be scored in-process
— on TPU the expensive part is the jitted training itself, and config
changes that keep shapes static reuse the compile cache across
individuals.  All randomness draws from the seeded PRNG streams."""

from __future__ import annotations

import dataclasses

import numpy as np

from . import prng
from .config import Config, root


@dataclasses.dataclass
class Gene:
    """One evolvable config leaf."""

    path: str                    # dotted path under the tree root
    lo: float
    hi: float
    is_int: bool = False

    def clip(self, v: float):
        v = float(np.clip(v, self.lo, self.hi))
        return int(round(v)) if self.is_int else v

    def sample(self, gen) -> float:
        return self.clip(gen.uniform(self.lo, self.hi))


@dataclasses.dataclass
class Individual:
    values: list
    fitness: float | None = None


class GeneticOptimizer:
    """Tournament-selection GA with blend crossover + gaussian mutation.

    ``evaluate(tree)`` receives a cloned config tree with the
    chromosome's values applied and returns a fitness (HIGHER is better —
    negate a loss).  The best tree is re-applied to the live ``root`` at
    the end (the reference applied the winning config the same way)."""

    def __init__(self, genes, evaluate, population_size=12,
                 generations=8, tournament=3, crossover_rate=0.7,
                 mutation_rate=0.15, mutation_sigma=0.2, elite=1,
                 tree: Config = root, stream="genetics"):
        self.genes = list(genes)
        self.evaluate = evaluate
        self.population_size = population_size
        self.generations = generations
        self.tournament = tournament
        self.crossover_rate = crossover_rate
        self.mutation_rate = mutation_rate
        self.mutation_sigma = mutation_sigma
        self.elite = elite
        self.tree = tree
        # a PRIVATE generator, not the registry's: candidate evaluation
        # through the Launcher reseeds every registered stream
        # (prng.seed_all), which would reset the GA's own draws each
        # generation and degenerate the search
        self.gen = prng.RandomGenerator(
            f"{stream}(private)", prng.get(stream).stream_seed)
        self.history: list[dict] = []
        self.best: Individual | None = None

    # -- chromosome ↔ config ------------------------------------------------
    def apply(self, values, tree: Config) -> Config:
        for gene, v in zip(self.genes, values):
            tree.set_path(gene.path, v)
        return tree

    def _fitness(self, ind: Individual) -> float:
        if ind.fitness is None:
            tree = self.tree.clone()
            self.apply(ind.values, tree)
            ind.fitness = float(self.evaluate(tree))
        return ind.fitness

    # -- GA operators --------------------------------------------------------
    def _select(self, population) -> Individual:
        picks = [population[self.gen.randint(0, len(population))]
                 for _ in range(self.tournament)]
        return max(picks, key=lambda i: i.fitness)

    def _crossover(self, a: Individual, b: Individual) -> list:
        if self.gen.uniform(0, 1) > self.crossover_rate:
            return list(a.values)
        mix = self.gen.uniform(0, 1, len(self.genes))
        return [g.clip(m * va + (1 - m) * vb)
                for g, va, vb, m in zip(self.genes, a.values, b.values,
                                        mix)]

    def _mutate(self, values) -> list:
        out = []
        for g, v in zip(self.genes, values):
            if self.gen.uniform(0, 1) < self.mutation_rate:
                span = g.hi - g.lo
                v = g.clip(v + self.gen.normal(0.0,
                                               self.mutation_sigma * span))
            out.append(v)
        return out

    def _score_population(self, population) -> None:
        """Fill in missing fitnesses — batched through the evaluator's
        ``evaluate_population`` when it has one (LauncherEvaluator runs
        candidates through parallel launcher processes, the reference
        genetics execution model), else serially."""
        pending = [i for i in population if i.fitness is None]
        if pending and hasattr(self.evaluate, "evaluate_population"):
            trees = []
            for ind in pending:
                tree = self.tree.clone()
                self.apply(ind.values, tree)
                trees.append(tree)
            fits = self.evaluate.evaluate_population(trees)
            for ind, f in zip(pending, fits):
                ind.fitness = float(f)
        for ind in population:
            self._fitness(ind)

    # -- main loop -----------------------------------------------------------
    def run(self) -> Individual:
        population = [Individual([g.sample(self.gen)
                                  for g in self.genes])
                      for _ in range(self.population_size)]
        for generation in range(self.generations):
            self._score_population(population)
            population.sort(key=lambda i: -i.fitness)
            self.best = population[0]
            self.history.append({
                "generation": generation,
                "best_fitness": population[0].fitness,
                "best_values": list(population[0].values),
                "mean_fitness": float(np.mean(
                    [i.fitness for i in population]))})
            if generation == self.generations - 1:
                break
            nxt = [Individual(list(i.values), i.fitness)
                   for i in population[:self.elite]]
            while len(nxt) < self.population_size:
                child = self._crossover(self._select(population),
                                        self._select(population))
                nxt.append(Individual(self._mutate(child)))
            population = nxt
        self.apply(self.best.values, self.tree)   # install the winner
        return self.best


# -- launcher-driven evaluation (reference: fitness = workflow result) -----
def _eval_main() -> None:
    """Subprocess entry: evaluate ONE candidate via the Launcher and
    print its fitness as JSON (spawned by LauncherEvaluator)."""
    import json
    import sys

    cfg = json.loads(sys.argv[1])
    if cfg.get("force_cpu"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    from .launcher import Launcher
    wf = Launcher(cfg["workflow"], epochs=cfg.get("epochs"),
                  backend=cfg.get("backend", "auto"),
                  seed=cfg.get("seed"),
                  overrides=cfg.get("overrides", ())).run()
    value = wf.decision.epoch_metrics[-1][cfg["metric"]]
    fitness = value if cfg.get("maximize") else -value
    print(json.dumps({"fitness": float(fitness)}))


class LauncherEvaluator:
    """Fitness = a workflow trained through the :class:`Launcher`
    (SURVEY.md §2.1 genetics row: the reference ran every candidate
    through the launcher; chromosome = config leaves).

    ``processes > 1`` evaluates candidates in parallel OS processes
    (each a fresh interpreter running :func:`_eval_main` with the
    chromosome as ``--set``-style overrides — the population-parallel
    execution the reference got from forked launchers).  ``processes=1``
    evaluates in-process: the candidate tree's values are applied to the
    global ``root``, the workflow is built and trained, and ``root`` is
    restored — same contract, no interpreter spin-up, shared jit cache."""

    def __init__(self, workflow: str, genes, metric="validation_n_err",
                 maximize=False, epochs=1, backend="xla",
                 seed: int | None = 4321, processes=1, force_cpu=False,
                 extra_overrides=()):
        self.workflow = workflow
        self.genes = list(genes)
        self.metric = metric
        self.maximize = maximize
        self.epochs = epochs
        self.backend = backend
        self.seed = seed
        self.processes = int(processes)
        self.force_cpu = force_cpu
        #: fixed ``path=value`` overrides shipped to every candidate —
        #: subprocesses start from module defaults, so experiment-level
        #: settings (dataset sizes, minibatch) must ride along
        self.extra_overrides = list(extra_overrides)
        # import the workflow module NOW so its setdefaults populate the
        # root tree — gene paths must resolve into real config (cloning
        # before the defaults exist would auto-create empty nodes and
        # corrupt the layers list)
        from .launcher import load_workflow_module
        load_workflow_module(workflow)

    def _overrides(self, tree) -> list[str]:
        return self.extra_overrides \
            + [f"{g.path}={tree.get(g.path)!r}" for g in self.genes]

    def _eval_inprocess(self, tree) -> float:
        import copy

        from .config import apply_overrides
        from .launcher import Launcher
        saved = copy.deepcopy(root.to_dict())
        saved_seed = prng._global_seed
        try:
            root.update(tree.to_dict())
            apply_overrides(self.extra_overrides)   # parity with the
            wf = Launcher(self.workflow, epochs=self.epochs,  # subprocess
                          backend=self.backend, seed=self.seed).run()
            value = wf.decision.epoch_metrics[-1][self.metric]
            return float(value if self.maximize else -value)
        finally:
            root.update(saved)
            # the Launcher reseeded the global streams for reproducible
            # candidate runs; restore the caller's seed (stream
            # *positions* are not restorable — documented caveat)
            prng.seed_all(saved_seed)

    def __call__(self, tree) -> float:
        return self.evaluate_population([tree])[0]

    def evaluate_population(self, trees) -> list:
        if self.processes <= 1:
            return [self._eval_inprocess(t) for t in trees]
        import json
        import subprocess
        import sys
        import tempfile
        import time

        def job(tree):
            cfg = {"workflow": self.workflow, "metric": self.metric,
                   "maximize": self.maximize, "epochs": self.epochs,
                   "backend": self.backend, "seed": self.seed,
                   "force_cpu": self.force_cpu,
                   "overrides": self._overrides(tree)}
            # temp files, not PIPEs: a chatty child must never block on
            # a full pipe buffer while the parent waits on poll()
            fout = tempfile.TemporaryFile(mode="w+t")
            ferr = tempfile.TemporaryFile(mode="w+t")
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "from znicz_tpu.genetics import _eval_main; _eval_main()",
                 json.dumps(cfg)],
                stdout=fout, stderr=ferr, text=True)
            return proc, fout, ferr

        results: list[float | None] = [None] * len(trees)
        queue = list(enumerate(trees))
        active: list[tuple] = []
        try:
            while queue or active:
                while queue and len(active) < self.processes:
                    i, tree = queue.pop(0)
                    active.append((i, *job(tree)))
                # reap whichever candidate finishes first — a slow
                # oldest process must not hold the slot (as-completed,
                # not FIFO)
                done = next((entry for entry in active
                             if entry[1].poll() is not None), None)
                if done is None:
                    time.sleep(0.2)
                    continue
                active.remove(done)
                i, proc, fout, ferr = done
                fout.seek(0)
                out = fout.read()
                ferr.seek(0)
                err = ferr.read()
                fout.close()
                ferr.close()
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"candidate evaluation failed "
                        f"(rc={proc.returncode}):\n{err[-2000:]}")
                for line in reversed(out.strip().splitlines()):
                    try:
                        results[i] = float(json.loads(line)["fitness"])
                        break
                    except (ValueError, KeyError, TypeError):
                        continue   # non-fitness JSON / stray output line
                else:
                    raise RuntimeError(
                        f"no fitness JSON in output:\n{out}")
        finally:
            for entry in active:         # no orphans on failure paths
                entry[1].kill()
                entry[2].close()
                entry[3].close()
        return results
