"""Overload defense in depth: deadlines, retry budgets, hedging, shedding.

PRs 1–8 built the fleet (batcher + breaker + replicas + promotion +
SPMD) but nothing kept it *well-behaved when demand exceeds capacity*:
a deadline set at admission never reached the engine, retries were
per-call with no fleet-wide budget (a latency blip triggers a retry
storm that amplifies the overload that caused it), a slow-but-not-sick
replica dragged p99 for every request routed to it, and the only
admission signal was a fixed queue bound.  This module is the one
robustness context a request carries end to end; the serving stack
consults it at every hop (docs/resilience.md "Overload defense"):

* :class:`Deadline` — an absolute monotonic deadline + criticality
  attached at admission (``X-Deadline-Ms`` / ``X-Criticality`` or the
  server default) and propagated via a contextvar across the
  batcher's thread hop; every stage calls :func:`check_deadline` and a
  request whose remaining budget cannot cover the next stage is
  rejected *early* instead of doing doomed work
  (``deadline_exceeded_total{stage}``).
* :class:`RetryBudget` — a process-wide token bucket refilled as a
  fraction of *successful* traffic (the SRE retry-budget rule):
  :class:`~znicz_tpu.resilience.retry.RetryPolicy` spends one token
  per retry, so under correlated failure retries self-limit at
  ``ratio`` of throughput instead of storming (``retry_budget_tokens``).
* :class:`HedgePolicy` — when a dispatch outlives the observed p95
  forward latency, :class:`~znicz_tpu.serving.replicas.
  EngineReplicaSet` fires ONE hedge on another healthy replica;
  first result wins, the loser is discarded and counted
  (``hedges_total{outcome}``) — the slow-replica tail collapses to
  roughly the hedge threshold.
* :class:`CoDelShedder` — CoDel-style adaptive admission keyed on
  *measured queue wait* (the signal the flight recorder already
  records): sustained wait above target escalates a brownout ladder
  that sheds ``sheddable`` traffic first, then ``default``, and
  ``critical`` never (``shed_total{criticality}``); any wait back
  under target resets it.
* drain state — graceful SIGTERM: stop admitting (:class:`Draining`
  → 503 + Retry-After), finish in-flight, then exit
  (``drain_state``: 0 serving, 1 draining, 2 drained).

Layering: this module depends only on the telemetry registry, so both
``resilience.retry`` below it and every ``serving`` module above it
can import it without cycles.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import threading
import time

from ..telemetry.registry import REGISTRY

#: the brownout ladder, least- to most-protected (X-Criticality)
CRITICALITIES = ("sheddable", "default", "critical")

_deadline_exceeded = REGISTRY.counter(
    "deadline_exceeded_total",
    "requests rejected or expired by end-to-end deadline enforcement, "
    "by the stage that refused the doomed work (admission | queue | "
    "dispatch | forward | retry | router)")
_budget_tokens = REGISTRY.gauge(
    "retry_budget_tokens",
    "tokens left in the process-wide retry budget (refilled as a "
    "fraction of successful calls; each retry and each hedge spends "
    "one — empty means retries are being denied)")
_hedges = REGISTRY.counter(
    "hedges_total",
    "hedged replica dispatches, by outcome (won = hedge answered "
    "first | lost = primary answered first | denied = retry budget "
    "empty | no_replica = no second healthy replica)")
_shed = REGISTRY.counter(
    "shed_total",
    "requests refused by the adaptive (CoDel-style) admission ladder, "
    "by criticality class")
_drain_state = REGISTRY.gauge(
    "drain_state",
    "graceful-shutdown progress: 0 serving, 1 draining (admission "
    "stopped, in-flight finishing), 2 drained cleanly — a drain that "
    "timed out with work still in flight stays at 1")

DRAIN_SERVING, DRAIN_DRAINING, DRAIN_DRAINED = 0, 1, 2
_drain_state.set(DRAIN_SERVING)


def set_drain_state(state: int) -> None:
    """Publish drain progress (``DRAIN_*``) to the metrics gauge."""
    _drain_state.set(int(state))


# -- typed refusals ---------------------------------------------------------

class DeadlineExceeded(Exception):
    """The request's end-to-end deadline passed; ``stage`` names the
    hop that noticed (the HTTP front answers 504 — the work was
    admitted, then ran out of budget mid-flight)."""

    def __init__(self, message: str, stage: str = "unknown"):
        super().__init__(message)
        self.stage = stage


class EarlyReject(Exception):
    """Admission refused BEFORE any work was done — the HTTP front
    answers 503 + ``Retry-After`` (never a hang, never doomed work).
    Subclasses say why; ``retry_after`` is the honest come-back time."""

    def __init__(self, message: str, retry_after: int = 1):
        super().__init__(message)
        self.retry_after = max(1, int(retry_after))


class DoomedDeadline(EarlyReject):
    """The request's remaining budget cannot cover the measured queue
    backlog + service time: serving it would only burn a device slot
    producing an answer nobody is waiting for."""


class Shed(EarlyReject):
    """The adaptive admission ladder refused this criticality class
    while queue wait stays above target (brownout)."""


class Draining(EarlyReject):
    """This replica is draining for shutdown: in-flight work finishes,
    new work must go to a peer."""


# -- deadline context -------------------------------------------------------

class Deadline:
    """One request's robustness context: absolute monotonic deadline
    (None = unbounded) + criticality class.  Immutable; cheap enough
    to attach to every request."""

    __slots__ = ("at", "criticality")

    def __init__(self, at: float | None = None,
                 criticality: str = "default"):
        if criticality not in CRITICALITIES:
            raise ValueError(f"criticality {criticality!r}; expected "
                             f"one of {CRITICALITIES}")
        self.at = at
        self.criticality = criticality

    @classmethod
    def from_ms(cls, deadline_ms: float | None,
                criticality: str = "default") -> "Deadline":
        """``deadline_ms`` is a budget from NOW; 0 means "already due"
        (immediate-or-fail), None means no deadline — the same
        contract the batcher has pinned since PR 1."""
        at = (time.monotonic() + float(deadline_ms) / 1e3
              if deadline_ms is not None else None)
        return cls(at, criticality)

    def remaining_s(self) -> float:
        return (float("inf") if self.at is None
                else self.at - time.monotonic())

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1e3

    def expired(self) -> bool:
        return self.at is not None and time.monotonic() > self.at

    def check(self, stage: str, need_s: float = 0.0) -> None:
        """Refuse the next hop when the remaining budget cannot cover
        it: raises :class:`DeadlineExceeded` (and counts the stage)
        when less than ``need_s`` remains."""
        if self.at is None:
            return
        if self.remaining_s() < need_s:
            note_deadline(stage)
            raise DeadlineExceeded(
                f"deadline exceeded at {stage} "
                f"({self.remaining_ms():.0f}ms of budget left, "
                f"{need_s * 1e3:.0f}ms needed)", stage=stage)


def note_deadline(stage: str) -> None:
    """Count one deadline refusal at ``stage`` (for callers that raise
    their own typed error, like the batcher's queue-expiry path)."""
    _deadline_exceeded.inc(stage=stage)


_deadline_var: contextvars.ContextVar[Deadline | None] = \
    contextvars.ContextVar("znicz_deadline", default=None)


def current_deadline() -> Deadline | None:
    """The deadline context of the current logical request, if any."""
    return _deadline_var.get()


@contextlib.contextmanager
def deadline_scope(deadline: Deadline | None):
    """Install ``deadline`` as the current context for this thread's
    work — the batcher enters it around each dispatched batch (using
    the LATEST rider deadline: the forward is still useful while any
    rider can use the result), and hedge workers re-enter it on their
    helper threads, where contextvars do not propagate by
    themselves."""
    token = _deadline_var.set(deadline)
    try:
        yield deadline
    finally:
        _deadline_var.reset(token)


def check_deadline(stage: str, need_s: float = 0.0) -> None:
    """The one call instrumented hops make — no-op without a
    deadline in context."""
    dl = _deadline_var.get()
    if dl is not None:
        dl.check(stage, need_s)


# -- retry budget -----------------------------------------------------------

class RetryBudget:
    """Process-wide token bucket bounding speculative work (retries
    AND hedges) to a fraction of successful traffic.

    The bucket starts full (``capacity`` tokens) so a fresh process
    can absorb its cold-start blips, then refills ``ratio`` tokens per
    recorded success — the steady-state invariant is the SRE rule
    «retries ≤ ratio × successes (+ the initial capacity)»: under a
    correlated failure where *nothing* succeeds, retries stop after
    ``capacity`` attempts fleet-process-wide instead of multiplying
    the overload.  Thread-safe; one instance per process is the
    intended topology (the serve CLI shares one across all replicas —
    a fleet-wide budget is the point, unlike breakers, which isolate
    per-replica failure domains)."""

    def __init__(self, ratio: float = 0.1, capacity: float = 100.0):
        if not 0.0 < ratio <= 1.0:
            raise ValueError(f"ratio must be in (0, 1], got {ratio}")
        if capacity < 1.0:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.ratio = float(ratio)
        self.capacity = float(capacity)
        self._lock = threading.Lock()
        self._tokens = self.capacity
        self._spent = 0
        self._denied = 0
        self._successes = 0
        _budget_tokens.set(self._tokens)

    def on_success(self) -> None:
        with self._lock:
            self._successes += 1
            self._tokens = min(self.capacity, self._tokens + self.ratio)
            tokens = self._tokens
        _budget_tokens.set(tokens)

    def try_spend(self, cost: float = 1.0) -> bool:
        """Take ``cost`` tokens for one retry/hedge; False (and a
        denied count) when the bucket cannot cover it — the caller
        must fail fast instead of storming."""
        with self._lock:
            if self._tokens < cost:
                self._denied += 1
                return False
            self._tokens -= cost
            self._spent += 1
            tokens = self._tokens
        _budget_tokens.set(tokens)
        return True

    def metrics(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "capacity": self.capacity, "ratio": self.ratio,
                    "spent": self._spent, "denied": self._denied,
                    "successes": self._successes}


_process_budget: RetryBudget | None = None
_process_budget_lock = threading.Lock()


def set_process_budget(budget: RetryBudget | None) -> None:
    """Install the budget the serve CLI built so introspection
    (``/statusz``, ``overload_status``) can report its level without
    threading the object through every layer."""
    global _process_budget
    with _process_budget_lock:
        _process_budget = budget


def process_budget() -> RetryBudget | None:
    with _process_budget_lock:
        return _process_budget


# -- hedged dispatch policy -------------------------------------------------

class HedgePolicy:
    """When to fire a second (hedged) attempt on another replica.

    Auto mode (default): hedge once a dispatch outlives the observed
    ``quantile`` (p95) of recorded forward latencies — tail-chasing
    only, so at most ~5% of dispatches ever hedge and the added load
    is bounded by construction.  Until ``min_samples`` latencies are
    recorded there is no trustworthy tail and no hedging.
    ``after_ms`` pins a fixed threshold instead (operator knob
    ``--hedge-after-ms``; also what a drill uses for determinism).

    ``budget`` (a :class:`RetryBudget`) gates every hedge like a
    retry: speculative work must not multiply an overload."""

    def __init__(self, quantile: float = 0.95, min_samples: int = 16,
                 after_ms: float | None = None,
                 budget: RetryBudget | None = None,
                 window: int = 512):
        if not 0.5 <= quantile < 1.0:
            raise ValueError(f"quantile must be in [0.5, 1), "
                             f"got {quantile}")
        self.quantile = float(quantile)
        self.min_samples = int(min_samples)
        self.after_ms = None if after_ms is None else float(after_ms)
        self.budget = budget
        self._lock = threading.Lock()
        self._lat_ms: collections.deque = collections.deque(
            maxlen=int(window))
        self._outcomes = collections.Counter()

    def record_ms(self, ms: float) -> None:
        """One observed replica forward latency (every worker records
        its own completion, winners and losers both, so hedging cannot
        bias the quantile it keys on)."""
        with self._lock:
            self._lat_ms.append(float(ms))

    def threshold_ms(self) -> float | None:
        """Current hedge trigger, or None when hedging must not fire
        (auto mode without enough samples yet)."""
        if self.after_ms is not None:
            return self.after_ms
        with self._lock:
            if len(self._lat_ms) < self.min_samples:
                return None
            lat = sorted(self._lat_ms)
        return lat[min(len(lat) - 1, int(len(lat) * self.quantile))]

    def note_outcome(self, outcome: str) -> None:
        _hedges.inc(outcome=outcome)
        with self._lock:
            self._outcomes[outcome] += 1

    def allow_hedge(self) -> bool:
        """Budget gate for one hedge (no budget configured = allowed;
        the p95 trigger already bounds hedge volume)."""
        if self.budget is None:
            return True
        if self.budget.try_spend():
            return True
        self.note_outcome("denied")
        return False

    def metrics(self) -> dict:
        with self._lock:
            out = dict(self._outcomes)
            n = len(self._lat_ms)
        return {"threshold_ms": self.threshold_ms(), "samples": n,
                "outcomes": out}


# -- adaptive load shedding -------------------------------------------------

class CoDelShedder:
    """CoDel-style admission control keyed on measured queue wait.

    The batcher feeds :meth:`note_queue_wait` with each dispatched
    batch's oldest-rider wait (the figure the PR-7 flight recorder
    already measures).  Standing wait above ``target_ms`` for a full
    ``interval_ms`` means the queue is not absorbing a burst but
    hiding an overload — each further full interval escalates the
    brownout ladder one level; ANY wait back under target resets it
    (CoDel's "standing queue" test, not an average):

    ==== ===============================================
    0    admit everything (healthy)
    1    shed ``sheddable`` requests
    2    shed ``sheddable`` + ``default`` — ``critical`` only
    ==== ===============================================

    ``critical`` traffic is never shed here — when even level 2
    cannot keep up, the bounded queue's 429 is the backstop.

    De-escalation has TWO paths, because wait samples only exist when
    batches dispatch: a sample back under target resets the ladder
    immediately, and a *quiet* interval with no samples at all steps
    it down one level (checked at admission).  Without the second
    path the ladder could latch: at level 2 all non-critical traffic
    is refused at admission, the queue drains, nothing dispatches,
    and no sample would ever arrive to reset it."""

    def __init__(self, target_ms: float = 100.0,
                 interval_ms: float = 500.0, clock=time.monotonic):
        if target_ms <= 0 or interval_ms <= 0:
            raise ValueError("target_ms and interval_ms must be > 0")
        self.target_ms = float(target_ms)
        self.interval_s = float(interval_ms) / 1e3
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._above_since: float | None = None
        self._last_note: float | None = None
        self._last_wait_ms: float | None = None
        self._shed_counts = collections.Counter()

    @property
    def level(self) -> int:
        with self._lock:
            self._decay_locked(self._clock())
            return self._level

    def note_queue_wait(self, wait_ms: float) -> None:
        with self._lock:
            # no decay here: a sample IS dispatch activity, however
            # sparse — only sample-free silence (seen from the read
            # side) de-escalates
            now = self._clock()
            prev = self._last_note
            self._last_note = now
            self._last_wait_ms = float(wait_ms)
            if wait_ms < self.target_ms:
                self._above_since = None
                self._level = 0
                return
            if prev is not None and now - prev >= 2 * self.interval_s:
                # a sample GAP of two-plus intervals breaks
                # "standing": an anchor left over from before an idle
                # stretch must not let the first sample of a fresh
                # burst escalate on its own.  (One interval is not a
                # gap — dispatch cadence under slow batches can
                # legitimately run at interval scale.)
                self._above_since = None
            if self._above_since is None:
                self._above_since = now
            elif now - self._above_since >= self.interval_s:
                self._level = min(2, self._level + 1)
                self._above_since = now

    def _decay_locked(self, now: float) -> None:
        """One level down per full interval WITHOUT a wait sample —
        silence means the queue is empty (nothing dispatching),
        which is the opposite of standing overload."""
        while self._level > 0 and self._last_note is not None \
                and now - self._last_note >= self.interval_s:
            self._level -= 1
            self._above_since = None
            self._last_note += self.interval_s

    def admit(self, criticality: str) -> bool:
        """Admission verdict for one request; a False already counted
        ``shed_total{criticality}`` (the caller just raises)."""
        with self._lock:
            self._decay_locked(self._clock())
            level = self._level
            shed = ((level >= 1 and criticality == "sheddable")
                    or (level >= 2 and criticality != "critical"))
            if shed:
                self._shed_counts[criticality] += 1
        if shed:
            _shed.inc(criticality=criticality)
        return not shed

    def metrics(self) -> dict:
        with self._lock:
            self._decay_locked(self._clock())
            return {"level": self._level,
                    "target_ms": self.target_ms,
                    "last_queue_wait_ms": self._last_wait_ms,
                    "shed": dict(self._shed_counts)}
