"""``python -m znicz_tpu chaos`` — serving-under-fault smoke mode.

Boots the real HTTP serving stack (engine + micro-batcher + server)
under a canned :class:`~.faults.FaultPlan`, drives traffic through the
whole breaker lifecycle, and verifies the graceful-degradation
contract end to end:

* with a persistent ``engine.forward`` fault every request still
  resolves — native-fallback 200 or 503 + Retry-After, never a raw 500
  and never a hang;
* ``/healthz`` leaves ``ok`` while the circuit is open (``degraded`` /
  ``open``);
* once the fault clears, a half-open probe closes the breaker and
  ``/healthz`` returns to ``ok``.

A second drill, ``--scenario reload``, smokes the durability layer
(docs/durability.md): a hot reload of a deterministically bit-rotted
artifact must roll back — verify fails, the generation stays put, the
old model keeps answering 200s with identical bytes — and a subsequent
good artifact must swap with zero downtime.

The third drill, ``--scenario promote``, is the closed-loop acceptance
(docs/promotion.md): a stand-in trainer keeps committing fresh
candidate ``.znn`` artifacts through the real atomic export path while
live traffic flows, and a :class:`~znicz_tpu.promotion.controller.
PromotionController` drives each one through verify → export → canary
reload → SLO watch — under injected transient faults at
``engine.forward``, ``promotion.export`` and ``promotion.slo_probe``
— then a deliberately-regressed candidate (it canaries clean but
latency-regresses under traffic, injected at ``engine.forward``) must
be auto-rolled-back within the SLO window.  Asserted: zero non-200
``/predict`` answers across the whole run, ≥N promotions landed, the
rollback restored the previous generation's exact bytes, and the
promotion ledger records every transition.

The fifth drill, ``--scenario zoo`` (tools/zoo_smoke.sh), is the
multi-tenant acceptance (docs/serving.md "Multi-tenant model zoo"):
three model families behind one server under a weight-residency
budget that forces eviction, mixed-criticality traffic with one
tenant latency-faulted (``zoo.model.<name>``) and one hot-reloaded
mid-burst — zero raw 500s, the critical tenant never shed, page-in
byte-identity, page-in p99 bounded by the warmup compile cost, and
per-model reload isolation all asserted.

The ``--scenario online`` drill (tools/online_smoke.sh) is the
live-data-loop acceptance (docs/online.md): a capturing server under
live traffic, the continual trainer replaying the capture ring in
bless/refuse rounds, the stock promotion controller deploying each
blessed candidate under transient faults — a poisoned round refused
at blessing, a blessed-but-toxic candidate rolled back by the SLO
watch with byte-identical outputs, the ``capture.append`` fail-open
contract fault-injected, plus the Kohonen serve-and-train phase.

The ``--scenario ha`` drill (tools/ha_smoke.sh) is the
highly-available fleet front acceptance (docs/fleet.md "Router high
availability"): a primary ``route --state-dir`` and a hot standby
over the same journal, the primary SIGKILLed mid-burst — the standby
takes the lease (exactly one epoch bump), adopts the journal's
children and serves within 2x the lease TTL; the resurrected old
primary rejoins as a FENCED standby whose stale mutations are
refused with 503 + Retry-After; zero raw 500s across the arc.

Exit code 0 when every invariant holds — tools/chaos_smoke.sh wires
this into CI-ish usage.  The same ``FaultPlan`` mechanism drives the
pytest ``chaos`` marker; this mode exists so an operator can smoke a
REAL server (their model, their knobs) without pytest.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np

from . import faults
from .breaker import CircuitBreaker
from .retry import RetryPolicy


def _write_demo_znn(path: str, fin: int = 4, hidden: int = 3,
                    classes: int = 2, seed: int = 7) -> None:
    """A tiny deterministic fc(tanh)+fc+softmax model — enough layers
    to exercise the full forward without slow jit compiles.  Committed
    through the real atomic publish (manifest + ``artifact.bitflip``
    chaos site), so corruption drills can rot it deterministically."""
    from ..export import ACT, KIND, _commit_znn, _pack_layer, \
        _write_header
    gen = np.random.default_rng(seed)
    w1 = gen.standard_normal((fin, hidden)).astype(np.float32)
    b1 = gen.standard_normal(hidden).astype(np.float32)
    w2 = gen.standard_normal((hidden, classes)).astype(np.float32)
    with open(path + ".tmp", "wb") as fh:
        _write_header(fh, 3)
        _pack_layer(fh, KIND["fc"], ACT["tanh"], [fin, hidden], w1, b1)
        _pack_layer(fh, KIND["fc"], ACT["linear"], [hidden, classes], w2)
        _pack_layer(fh, KIND["softmax"], 0, [])
    _commit_znn(path)


def _post(url: str, payload: dict, timeout: float = 30.0,
          headers: dict | None = None):
    """(status, body) — errors become their status code, a connection
    hang becomes the invariant failure it is."""
    req = urllib.request.Request(
        url + "predict", json.dumps(payload).encode(),
        {"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _health(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url + "healthz", timeout=timeout) as r:
        return json.loads(r.read())


def _admin_reload(url: str, model: str, timeout: float = 60.0):
    """(status, body) of a synchronous ``POST /admin/reload``."""
    req = urllib.request.Request(
        url + "admin/reload",
        json.dumps({"model": model, "wait": True}).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _reload_scenario(args) -> int:
    """``--scenario reload`` — the corruption→rollback drill
    (docs/durability.md): serve v1, hot-reload a bit-rotted v2 (the
    ``artifact.bitflip`` fault site fires during its export, so the rot
    is deterministic) and assert the rollback contract — generation
    unchanged, the OLD model still answering 200s with identical bytes,
    ``/healthz`` reporting the failed outcome — then land a good v3 and
    assert the zero-downtime swap."""
    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        v1 = os.path.join(tmp, "v1.znn")
        _write_demo_znn(v1)
        engine = ServingEngine(v1, backend="jax", buckets=(1, 2))
        server = ServingServer(engine, max_wait_ms=1.0).start()
        try:
            status, body, _ = _post(server.url, {"inputs": x})
            y0 = body.get("outputs")
            if status != 200:
                bad.append(f"baseline predict got {status}")
            # v2 rots as it lands on disk: one flipped byte under a
            # live manifest — exactly what verify-on-load must catch
            v2 = os.path.join(tmp, "v2.znn")
            plan = faults.FaultPlan([faults.FaultSpec(
                "artifact.bitflip", times=1,
                message="chaos: storage rot on the new artifact")],
                seed=7)
            with plan:
                _write_demo_znn(v2, seed=11)
            if plan.snapshot().get("artifact.bitflip:error", 0) != 1:
                bad.append("bitflip fault never fired — v2 is clean "
                           "and the drill proves nothing")
            status, rec = _admin_reload(server.url, v2)
            last = (rec.get("last_reload") or {})
            print(json.dumps({"phase": "corrupt-reload",
                              "status": status, "reload": last,
                              "generation": rec.get("model_generation")}))
            if last.get("outcome") != "verify_failed":
                bad.append(f"corrupt reload outcome "
                           f"{last.get('outcome')!r}, expected "
                           f"'verify_failed'")
            if rec.get("model_generation") != 1:
                bad.append(f"generation moved to "
                           f"{rec.get('model_generation')} on a failed "
                           f"reload")
            for i in range(args.requests):
                status, body, _ = _post(server.url, {"inputs": x})
                if status != 200:
                    bad.append(f"post-rollback request {i} got {status}")
                elif body.get("outputs") != y0:
                    bad.append(f"post-rollback request {i} answered "
                               f"with different bytes — generations "
                               f"mixed")
            health = _health(server.url)
            if health["status"] != "ok":
                bad.append(f"healthz {health['status']!r} after a "
                           f"rolled-back reload, expected 'ok'")
            if (health.get("last_reload") or {}).get("outcome") \
                    != "verify_failed":
                bad.append("healthz does not report the failed reload")
            # a good artifact swaps with zero downtime
            v3 = os.path.join(tmp, "v3.znn")
            _write_demo_znn(v3, seed=23)
            status, rec = _admin_reload(server.url, v3)
            last = (rec.get("last_reload") or {})
            print(json.dumps({"phase": "good-reload", "status": status,
                              "reload": last,
                              "generation": rec.get("model_generation")}))
            if last.get("outcome") != "ok" \
                    or rec.get("model_generation") != 2:
                bad.append(f"good reload did not swap: {last}")
            status, body, _ = _post(server.url, {"inputs": x})
            if status != 200:
                bad.append(f"post-swap predict got {status}")
            elif body.get("outputs") == y0:
                bad.append("post-swap outputs identical to v1 — the "
                           "new weights never took")
            print(json.dumps({
                "scenario": "reload", "ok": not bad, "violations": bad,
                "engine": {k: v for k, v in engine.metrics().items()
                           if k in ("generation", "reloads")}}))
        finally:
            server.stop()
            engine.close()
    return 1 if bad else 0


def _promote_scenario(args) -> int:
    """``--scenario promote`` — train-while-serving through N
    promotions with fault injection plus one deliberately-regressed
    candidate; the zero-500 / verified-rollback acceptance of
    docs/promotion.md."""
    import collections
    import threading

    from ..promotion import (DirectorySource, EngineTarget,
                             PromotionController, SLOPolicy)
    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        cands = os.path.join(tmp, "candidates")
        deploy = os.path.join(tmp, "deploy")
        os.makedirs(cands)
        v0 = os.path.join(tmp, "v0.znn")
        _write_demo_znn(v0, seed=5)
        engine = ServingEngine(v0, backend="jax", buckets=(1, 2))
        server = ServingServer(engine, max_wait_ms=1.0).start()
        policy = SLOPolicy(
            window_s=args.watch_s,
            probe_interval_s=max(0.1, args.watch_s / 6.0),
            max_p99_ms=args.max_p99_ms, max_error_rate=0.05,
            min_samples=3)
        controller = PromotionController(
            DirectorySource(cands), EngineTarget(server=server),
            deploy_dir=deploy, policy=policy, poll_interval_s=0.1,
            max_consecutive_failures=3)
        stop = threading.Event()
        codes: list[int] = []
        mu = threading.Lock()

        def traffic():
            # continuous live traffic for the whole run — the zero-500
            # assertion is over every answer this loop collects
            while not stop.is_set():
                try:
                    status, _body, _h = _post(server.url,
                                              {"inputs": x},
                                              timeout=30.0)
                except Exception:
                    status = -1        # hang/conn drop = the failure
                with mu:
                    codes.append(status)
                stop.wait(0.01)

        thread = threading.Thread(target=traffic, daemon=True)
        thread.start()
        try:
            # let the first jit compile land so the SLO baseline sees
            # steady-state latency, not the cold start
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                with mu:
                    if len(codes) >= 5:
                        break
                time.sleep(0.05)
            outcomes = []
            for k in range(args.promotions):
                # the stand-in trainer: a fresh candidate through the
                # real atomic export path, promoted under transient
                # faults at every new seam (each absorbed by a retry
                # tier, so the promotion still lands)
                plan = faults.FaultPlan([
                    faults.FaultSpec("engine.forward", times=1,
                                     message="chaos: transient device "
                                             "fault"),
                    faults.FaultSpec("promotion.export", times=1,
                                     message="chaos: export blip"),
                    faults.FaultSpec("promotion.slo_probe", times=1,
                                     message="chaos: probe blip")],
                    seed=100 + k)
                with plan:
                    _write_demo_znn(os.path.join(cands,
                                                 f"cand{k + 1}.znn"),
                                    seed=30 + k)
                    outcome = controller.run_once()
                outcomes.append(outcome)
                print(json.dumps({"phase": f"promotion-{k + 1}",
                                  "outcome": outcome,
                                  "generation": engine.generation,
                                  "fired": plan.snapshot()}))
                if outcome != "promoted":
                    bad.append(f"candidate {k + 1} outcome {outcome!r},"
                               f" expected 'promoted'")
            status, body, _ = _post(server.url, {"inputs": x})
            y_good = body.get("outputs")
            gen_good = engine.generation
            if status != 200:
                bad.append(f"post-promotions probe got {status}")
            # the regressed candidate: canaries clean (well-formed,
            # finite) but every live forward slows by bad_latency_s —
            # the SLO watch must catch it and roll back while the
            # previous artifact still sits in the deploy dir
            _write_demo_znn(os.path.join(cands, "cand-bad.znn"),
                            seed=99)
            plan = faults.FaultPlan([faults.FaultSpec(
                "engine.forward", kind="latency",
                latency_s=args.bad_latency_s,
                message="chaos: regressed candidate")], seed=7)
            with plan:
                outcome = controller.run_once()
            print(json.dumps({"phase": "bad-candidate",
                              "outcome": outcome,
                              "generation": engine.generation,
                              "fired": plan.snapshot()}))
            if outcome != "rolled_back":
                bad.append(f"bad candidate outcome {outcome!r}, "
                           f"expected 'rolled_back'")
            status, body, _ = _post(server.url, {"inputs": x})
            if status != 200:
                bad.append(f"post-rollback probe got {status}")
            elif body.get("outputs") != y_good:
                bad.append("post-rollback outputs differ from the "
                           "blessed generation — rollback did not "
                           "restore the previous bytes")
            if engine.generation != gen_good + 2:
                bad.append(f"generation {engine.generation} after "
                           f"rollback, expected {gen_good + 2} "
                           f"(bad swap + rollback swap)")
            health = _health(server.url)
            promo = health.get("promotion") or {}
            if promo.get("state") != "rolled_back" \
                    or promo.get("last_outcome") != "rolled_back":
                bad.append(f"healthz promotion block does not report "
                           f"the rollback: {promo}")
        finally:
            stop.set()
            thread.join(10.0)
            server.stop()
            engine.close()
        with mu:
            answered = list(codes)
        non200 = collections.Counter(c for c in answered if c != 200)
        if non200:
            bad.append(f"non-200 answers under promotion chaos: "
                       f"{dict(non200)} of {len(answered)}")
        # the ledger is the audit trail: every candidate must show its
        # state transitions and exactly the expected outcomes
        entries = controller.ledger.entries()
        outs = [e for e in entries if e.get("event") == "outcome"]
        n_promoted = sum(1 for e in outs if e["outcome"] == "promoted")
        n_rolled = sum(1 for e in outs if e["outcome"] == "rolled_back")
        if n_promoted != args.promotions or n_rolled != 1:
            bad.append(f"ledger outcomes: {n_promoted} promoted / "
                       f"{n_rolled} rolled_back, expected "
                       f"{args.promotions} / 1")
        states = {e.get("state") for e in entries
                  if e.get("event") == "state"}
        for want in ("verifying", "exporting", "canarying", "watching"):
            if want not in states:
                bad.append(f"ledger never recorded the {want!r} state")
        if not any(e.get("event") == "rollback" for e in entries):
            bad.append("ledger has no rollback event")
        print(json.dumps({
            "scenario": "promote", "ok": not bad, "violations": bad,
            "requests": len(answered), "outcomes": outcomes + [outcome],
            "promotion": controller.status(),
            "ledger_events": len(entries)}))
    return 1 if bad else 0


def _online_scenario(args) -> int:
    """``--scenario online`` — the live-data-loop acceptance
    (docs/online.md): a REAL capturing server, a REAL continual
    trainer and a REAL promotion watcher close the whole loop in one
    drill.

    Phase A (fc fine-tune): live traffic flows through a server whose
    tap appends to the capture ring; the OnlineTrainer replays it in
    bounded rounds (held-back bless judgment, TrainerCheckpointer
    steps, candidate exports) and the stock PromotionController
    canary-deploys each blessed candidate — under transient faults at
    ``engine.forward``, ``promotion.export`` and
    ``promotion.slo_probe``.  Then a poisoned round (shuffled labels,
    exploded lr ⇒ genuinely regressed held-back eval) must be REFUSED
    at blessing (no candidate appears), and a blessed-but-toxic
    candidate (clean eval, latency-faulted in production) must be
    rolled back by the SLO watch with byte-identical post-rollback
    outputs.  The capture tap's fail-open contract is fault-injected
    (``capture.append``) under live traffic.  Asserted: zero non-200
    answers for the whole run, ≥N promotions whose candidates were
    trained IN THIS RUN from replayed traffic, the refused round
    exported nothing, the ring honored its byte budget, and blessed
    checkpoint steps carry durability manifests.

    Phase B (Kohonen serve-and-train, the paper's online unit): a
    served SOM head adapts online to clustered replay traffic
    (quantization error improving), its blessed codebook exports,
    promotes onto the live server, and the post-adaptation artifact
    round-trips export → promotion → byte-identical serving.
    """
    import collections
    import threading

    from .. import durability
    from ..online.capture import CaptureLog
    from ..online.som import OnlineSom, read_som_znn
    from ..online.trainer import OnlineTrainer
    from ..promotion import (DirectorySource, EngineTarget,
                             PromotionController, SLOPolicy)
    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer
    from ..serving.zoo import write_demo_model

    bad: list[str] = []

    def policy():
        return SLOPolicy(
            window_s=args.watch_s,
            probe_interval_s=max(0.1, args.watch_s / 6.0),
            max_p99_ms=args.max_p99_ms, max_error_rate=0.05,
            min_samples=3)

    class Traffic:
        """Seeded live-traffic loop against one server; every answer
        code is collected — the zero-non-200 assertion's evidence."""

        def __init__(self, url: str, make_input):
            self.url = url
            self.make_input = make_input
            self.codes: list[int] = []
            self.mu = threading.Lock()
            self.stop = threading.Event()
            self.thread = threading.Thread(target=self._run,
                                           daemon=True)

        def _run(self):
            i = 0
            while not self.stop.is_set():
                try:
                    status, _b, _h = _post(self.url,
                                           {"inputs":
                                            self.make_input(i)},
                                           timeout=30.0)
                except Exception:
                    status = -1
                with self.mu:
                    self.codes.append(status)
                i += 1
                self.stop.wait(0.002)

        def start(self):
            self.thread.start()
            return self

        def finish(self) -> collections.Counter:
            self.stop.set()
            self.thread.join(10.0)
            with self.mu:
                return collections.Counter(c for c in self.codes
                                           if c != 200)

    cap_budget = 262_144
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        # ---- phase A: the fc fine-tune loop -------------------------
        v0 = os.path.join(tmp, "v0.znn")
        _write_demo_znn(v0, seed=5)
        capdir = os.path.join(tmp, "capture")
        cands = os.path.join(tmp, "candidates")
        ckpts = os.path.join(tmp, "checkpoints")
        deploy = os.path.join(tmp, "deploy")
        os.makedirs(cands)
        capture = CaptureLog(capdir, max_bytes=cap_budget, sample=1.0)
        engine = ServingEngine(v0, backend="jax", buckets=(1, 2))
        server = ServingServer(engine, max_wait_ms=1.0,
                               capture=capture).start()
        controller = PromotionController(
            DirectorySource(cands), EngineTarget(server=server),
            deploy_dir=deploy, policy=policy(), poll_interval_s=0.1,
            max_consecutive_failures=3)
        pool = np.random.default_rng(11).standard_normal(
            (64, 4)).astype(np.float32)
        traffic = Traffic(server.url,
                          lambda i: [pool[i % len(pool)].tolist()]
                          ).start()
        trainer = None
        try:
            # warm: let the first compiles land and the tap fill
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                with traffic.mu:
                    if len(traffic.codes) >= 50:
                        break
                time.sleep(0.05)
            # fail-open: the tap erroring under live traffic must not
            # surface in a single answer
            with traffic.mu:
                before = len(traffic.codes)
            plan = faults.FaultPlan([faults.FaultSpec(
                "capture.append", times=8,
                message="chaos: capture tap failure")], seed=3)
            with plan:
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    if plan.snapshot().get(
                            "capture.append:error", 0) >= 8:
                        break
                    time.sleep(0.05)
            fired = plan.snapshot().get("capture.append:error", 0)
            with traffic.mu:
                during = traffic.codes[before:]
            if fired < 8:
                bad.append(f"capture.append fault fired {fired}x, "
                           f"expected 8 — fail-open unproven")
            if any(c != 200 for c in during):
                bad.append(f"capture faults leaked into answers: "
                           f"{collections.Counter(during)}")
            drop_before = capture.metrics()["dropped_error"]
            if drop_before < fired:
                bad.append(f"only {drop_before} capture drops counted "
                           f"for {fired} injected faults")
            trainer = OnlineTrainer(
                v0, capdir, candidates_dir=cands,
                checkpoint_dir=ckpts, round_samples=96,
                min_round_samples=32, holdback_every=8,
                poll_timeout_s=15.0, seed=3)
            promoted_cands = []
            for k in range(args.promotions):
                plan = faults.FaultPlan([
                    faults.FaultSpec("engine.forward", times=1,
                                     message="chaos: transient device "
                                             "fault"),
                    faults.FaultSpec("promotion.export", times=1,
                                     message="chaos: export blip"),
                    faults.FaultSpec("promotion.slo_probe", times=1,
                                     message="chaos: probe blip")],
                    seed=100 + k)
                with plan:
                    round_out = {"outcome": "starved"}
                    for _ in range(8):       # bounded traffic wait
                        round_out = trainer.run_round()
                        if round_out["outcome"] != "starved":
                            break
                    outcome = controller.run_once()
                print(json.dumps({"phase": f"online-promotion-{k + 1}",
                                  "round": round_out,
                                  "outcome": outcome,
                                  "generation": engine.generation,
                                  "fired": plan.snapshot()}))
                if round_out["outcome"] != "blessed":
                    bad.append(f"round {k + 1} outcome "
                               f"{round_out['outcome']!r}, expected "
                               f"'blessed'")
                else:
                    promoted_cands.append(round_out["candidate"])
                if outcome != "promoted":
                    bad.append(f"candidate {k + 1} outcome "
                               f"{outcome!r}, expected 'promoted'")
            # every promotion's candidate was trained IN THIS RUN from
            # replayed traffic (the trainer's own export naming)
            for path in promoted_cands:
                if path is None or not os.path.basename(
                        path).startswith("online-"):
                    bad.append(f"promoted candidate {path!r} did not "
                               f"come from the online trainer")
            # blessed checkpoint steps carry durability manifests (the
            # bless mark CheckpointSource keys on)
            steps = [n for n in os.listdir(ckpts) if n.isdigit()] \
                if os.path.isdir(ckpts) else []
            if not steps:
                bad.append("no blessed checkpoint steps on disk")
            for n in steps:
                if durability.read_manifest(
                        os.path.join(ckpts, n)) is None:
                    bad.append(f"checkpoint step {n} has no "
                               f"durability manifest — not blessed")
            x_probe = {"inputs": [pool[0].tolist()]}
            status, body, _ = _post(server.url, x_probe)
            y_good = body.get("outputs")
            gen_good = engine.generation
            if status != 200:
                bad.append(f"post-promotions probe got {status}")
            # the poisoned round: shuffled labels at an exploded lr —
            # a genuine held-back regression the blessing must refuse,
            # with NO candidate appearing for the watcher
            n_cands = len(os.listdir(cands))
            round_out = {"outcome": "starved"}
            for _ in range(8):
                round_out = trainer.run_round(poison_labels=True)
                if round_out["outcome"] != "starved":
                    break
            print(json.dumps({"phase": "poisoned-round",
                              "round": round_out}))
            if round_out["outcome"] != "refused":
                bad.append(f"poisoned round outcome "
                           f"{round_out['outcome']!r}, expected "
                           f"'refused'")
            if len(os.listdir(cands)) != n_cands:
                bad.append("the refused round exported a candidate")
            if controller.run_once() is not None:
                bad.append("the promotion watcher found work after a "
                           "refused round")
            # a blessed-but-toxic candidate: clean held-back eval, but
            # latency-regressed in production — the SLO watch must
            # roll it back and restore the previous bytes
            round_out = {"outcome": "starved"}
            for _ in range(8):
                round_out = trainer.run_round()
                if round_out["outcome"] != "starved":
                    break
            if round_out["outcome"] != "blessed":
                bad.append(f"pre-toxic round outcome "
                           f"{round_out['outcome']!r}, expected "
                           f"'blessed'")
            plan = faults.FaultPlan([faults.FaultSpec(
                "engine.forward", kind="latency",
                latency_s=args.bad_latency_s,
                message="chaos: toxic candidate")], seed=7)
            with plan:
                outcome = controller.run_once()
            print(json.dumps({"phase": "toxic-candidate",
                              "outcome": outcome,
                              "generation": engine.generation,
                              "fired": plan.snapshot()}))
            if outcome != "rolled_back":
                bad.append(f"toxic candidate outcome {outcome!r}, "
                           f"expected 'rolled_back'")
            status, body, _ = _post(server.url, x_probe)
            if status != 200:
                bad.append(f"post-rollback probe got {status}")
            elif body.get("outputs") != y_good:
                bad.append("post-rollback outputs differ from the "
                           "last promoted generation — rollback did "
                           "not restore the previous bytes")
            if engine.generation != gen_good + 2:
                bad.append(f"generation {engine.generation} after "
                           f"rollback, expected {gen_good + 2}")
        finally:
            non200 = traffic.finish()
            server.stop()
            capture.close()
            if trainer is not None:
                trainer.close()
            engine.close()
        if non200:
            bad.append(f"non-200 answers under the online loop: "
                       f"{dict(non200)}")
        cap_m = capture.metrics()
        if cap_m["bytes"] > cap_budget:
            bad.append(f"capture ring holds {cap_m['bytes']} bytes, "
                       f"budget {cap_budget}")
        outs = [e for e in controller.ledger.entries()
                if e.get("event") == "outcome"]
        n_promoted = sum(1 for e in outs
                         if e["outcome"] == "promoted")
        n_rolled = sum(1 for e in outs
                       if e["outcome"] == "rolled_back")
        if n_promoted != args.promotions or n_rolled != 1:
            bad.append(f"ledger outcomes: {n_promoted} promoted / "
                       f"{n_rolled} rolled_back, expected "
                       f"{args.promotions} / 1")
        print(json.dumps({"phase": "fc-loop-summary", "ok": not bad,
                          "violations": list(bad),
                          "capture": cap_m,
                          "trainer": trainer.status()
                          if trainer is not None else None}))

        # ---- phase B: Kohonen serve-and-train -----------------------
        som_znn = os.path.join(tmp, "som.znn")
        write_demo_model(som_znn, "kohonen", seed=7)
        cap2 = os.path.join(tmp, "capture-som")
        cands2 = os.path.join(tmp, "candidates-som")
        deploy2 = os.path.join(tmp, "deploy-som")
        capture2 = CaptureLog(cap2, max_bytes=cap_budget, sample=1.0)
        engine2 = ServingEngine(som_znn, backend="jax", buckets=(1, 2))
        server2 = ServingServer(engine2, max_wait_ms=1.0,
                                capture=capture2).start()
        controller2 = PromotionController(
            DirectorySource(cands2), EngineTarget(server=server2),
            deploy_dir=deploy2, policy=policy(), poll_interval_s=0.1,
            max_consecutive_failures=3)
        rng = np.random.default_rng(23)
        centers = (2.5 * rng.standard_normal((4, 6))).astype(
            np.float32)
        jitter = rng.standard_normal((256, 6)).astype(np.float32)

        def som_input(i):
            row = centers[i % 4] + 0.15 * jitter[i % len(jitter)]
            return [row.astype(np.float32).tolist()]

        traffic2 = Traffic(server2.url, som_input).start()
        try:
            som = OnlineSom(som_znn, cap2, candidates_dir=cands2,
                            round_samples=64, min_round_samples=16,
                            holdback_every=8, poll_timeout_s=15.0,
                            seed=5)
            w0 = som.weights.copy()
            blessed = 0
            qes = []
            for _ in range(10):
                out = som.run_round()
                if out["outcome"] == "blessed":
                    blessed += 1
                    qes.append(out["qe"])
                if blessed >= 2:
                    break
            print(json.dumps({"phase": "som-adapt",
                              "status": som.status(), "qes": qes}))
            if blessed < 2:
                bad.append(f"SOM blessed only {blessed} round(s) of "
                           f"10, expected >= 2")
            if np.array_equal(w0, som.weights):
                bad.append("the served SOM never adapted — weights "
                           "unchanged after online rounds")
            outcome = controller2.run_once()
            print(json.dumps({"phase": "som-promotion",
                              "outcome": outcome,
                              "generation": engine2.generation}))
            if outcome != "promoted":
                bad.append(f"SOM candidate outcome {outcome!r}, "
                           f"expected 'promoted'")
            # round-trip: the deployed artifact IS the adapted
            # codebook, bit for bit, and serving it is deterministic
            cand = os.path.join(cands2, f"som-{som.step:06d}.znn")
            if not np.array_equal(read_som_znn(cand), som.weights):
                bad.append("exported SOM candidate differs from the "
                           "adapted codebook — the export round-trip "
                           "is lossy")
            probe = {"inputs": som_input(0)}
            st1, b1, _ = _post(server2.url, probe)
            st2, b2, _ = _post(server2.url, probe)
            if st1 != 200 or st2 != 200 or b1 != b2:
                bad.append(f"post-promotion SOM serving is not "
                           f"byte-deterministic ({st1}/{st2})")
            # ...and re-installing the SAME artifact answers the SAME
            # bytes: export → promotion → serving is a fixed point
            deployed = [os.path.join(deploy2, n)
                        for n in sorted(os.listdir(deploy2))
                        if n.endswith(".znn")]
            rec = engine2.reload(deployed[-1])
            if rec["outcome"] != "ok":
                bad.append(f"re-reload of the deployed SOM artifact "
                           f"failed: {rec}")
            st3, b3, _ = _post(server2.url, probe)
            if st3 != 200 or b3 != b1:
                bad.append("re-installing the deployed SOM artifact "
                           "changed the served bytes — the promotion "
                           "round-trip is not byte-identical")
        finally:
            non200b = traffic2.finish()
            server2.stop()
            capture2.close()
            engine2.close()
        if non200b:
            bad.append(f"non-200 answers under SOM serve-and-train: "
                       f"{dict(non200b)}")
        print(json.dumps({"scenario": "online", "ok": not bad,
                          "violations": bad}))
    return 1 if bad else 0


def _overload_scenario(args) -> int:
    """``--scenario overload`` — the overload-defense acceptance
    (docs/resilience.md "Overload defense"): sustained offered load
    well past capacity against a 2-replica fleet with ONE
    latency-faulted replica (``replica.slow.0``) plus a low-p
    transient ``engine.forward`` error fault, driven twice — hedging
    off, then on — and once more for the graceful drain.  Asserted:

    * zero hangs (every request resolves within the client bound) and
      zero raw 500s — the only answers are 200 / 429 / 503 / 504;
    * every 429/503 carries ``Retry-After``;
    * the shed ladder fired, and only against sheddable/default
      traffic — ``critical`` is never shed adaptively;
    * hedges fired, and hedged p99 is measurably below unhedged p99
      under the SAME fault and load;
    * fleet-wide retries stayed within the retry budget's invariant
      (spent ≤ capacity + ratio × successes);
    * SIGTERM-style drain: the in-flight request completes 200 while
      new admissions get 503 + Retry-After, then the process state
      reaches ``drain_state=2``.
    """
    import collections
    import threading

    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer
    from ..serving.replicas import EngineReplicaSet
    from ..telemetry.registry import REGISTRY
    from . import overload

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    crit_cycle = ("sheddable", "default", "default", "critical")

    def run_phase(model: str, hedged: bool) -> dict:
        # roomy capacity: with ONE of TWO replicas slow, hedging is
        # not a 5%-tail affair but ~half of dispatches — the drill
        # asserts the budget INVARIANT (spent ≤ capacity + ratio ×
        # successes), not starvation, which would just re-expose the
        # slow replica and muddy the p99 comparison
        budget = overload.RetryBudget(ratio=args.budget_ratio,
                                      capacity=500.0)

        def factory(i):
            # per-replica breaker/retry state, ONE shared budget —
            # the fleet-wide cap is the thing under test
            return ServingEngine(
                model, backend="jax", buckets=(1, 2, 4),
                retry=RetryPolicy(max_attempts=2, base_delay_s=0.005,
                                  max_delay_s=0.02, budget=budget),
                breaker=CircuitBreaker(failure_threshold=10,
                                       cooldown_s=0.5))

        hedge = (overload.HedgePolicy(after_ms=args.hedge_after_ms,
                                      budget=budget)
                 if hedged else None)
        engine = EngineReplicaSet(factory, 2, hedge=hedge)
        server = ServingServer(
            engine, max_batch=4, max_wait_ms=1.0, max_queue=24,
            default_deadline_ms=5000.0, shed_target_ms=25.0,
            shed_interval_ms=100.0).start()
        plan = faults.FaultPlan([
            faults.FaultSpec("replica.slow.0", kind="latency",
                             latency_s=args.slow_s,
                             message="chaos: slow replica"),
            faults.FaultSpec("engine.forward", p=0.1,
                             message="chaos: transient device "
                                     "fault")], seed=11)
        answers = []          # (code, latency_s, retry_after_present,
        mu = threading.Lock()  # criticality, done_at)
        stop = threading.Event()
        retries_before = _retry_total()

        def client(ci: int):
            k = 0
            while not stop.is_set():
                crit = crit_cycle[(ci + k) % len(crit_cycle)]
                k += 1
                t0 = time.monotonic()
                try:
                    status, _b, headers = _post(
                        server.url, {"inputs": x}, timeout=20.0,
                        headers={"X-Criticality": crit})
                except Exception:
                    status, headers = -1, {}   # hang/drop = failure
                done = time.monotonic()
                with mu:
                    answers.append((status, done - t0,
                                    "Retry-After" in headers, crit,
                                    done))

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(args.clients)]
        try:
            with plan:
                # one warm request per bucket shape before the storm,
                # so jit compiles don't masquerade as tail latency
                _post(server.url, {"inputs": x}, timeout=60.0)
                t_start = time.monotonic()
                for t in threads:
                    t.start()
                stop.wait(args.duration_s)
        finally:
            stop.set()
            for t in threads:
                t.join(30.0)
            metrics = server.metrics()
            server.stop()
            engine.close()
        # p99 over the STEADY state: the first second is the shed
        # ladder finding its level while the queue fills — both phases
        # pay it identically, and it would otherwise drown the
        # hedging-vs-not signal the drill exists to measure
        lat200 = sorted(lat for code, lat, _ra, _c, done in answers
                        if code == 200 and done - t_start > 1.0)
        p99 = (lat200[min(len(lat200) - 1, int(len(lat200) * 0.99))]
               if lat200 else None)
        return {"answers": answers, "p99_s": p99,
                "hedge": (engine.hedge_status() or {}),
                "shed": (metrics.get("shedder") or {}),
                "budget": budget.metrics(),
                "retries": _retry_total() - retries_before,
                "fired": plan.snapshot()}

    def _retry_total() -> int:
        snap = REGISTRY.as_dict().get("retry_attempts_total", 0)
        return int(sum(snap.values()) if isinstance(snap, dict)
                   else snap)

    def check_answers(phase: str, result: dict) -> None:
        codes = collections.Counter(c for c, _l, _ra, _cr, _d
                                    in result["answers"])
        if codes.get(-1):
            bad.append(f"{phase}: {codes[-1]} request(s) hung or "
                       f"dropped the connection")
        raw = {c for c in codes if c not in (200, 429, 503, 504, -1)}
        if raw:
            bad.append(f"{phase}: raw failure codes {sorted(raw)} "
                       f"(contract allows 200/429/503/504)")
        missing_ra = sum(1 for c, _l, ra, _cr, _d in result["answers"]
                         if c in (429, 503) and not ra)
        if missing_ra:
            bad.append(f"{phase}: {missing_ra} shed/backpressure "
                       f"answer(s) without Retry-After")
        b = result["budget"]
        if b["spent"] > b["capacity"] + b["ratio"] * b["successes"]:
            bad.append(f"{phase}: retries outspent the budget "
                       f"invariant: {b}")
        shed = result["shed"].get("shed") or {}
        if shed.get("critical"):
            bad.append(f"{phase}: critical traffic was shed "
                       f"adaptively: {shed}")
        print(json.dumps({"phase": phase, "codes": dict(codes),
                          "p99_ms": (round(result["p99_s"] * 1e3, 1)
                                     if result["p99_s"] else None),
                          "shed": shed, "hedge": result["hedge"],
                          "budget": b, "retries": result["retries"],
                          "fired": result["fired"]}))

    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        model = os.path.join(tmp, "demo.znn")
        _write_demo_znn(model)
        unhedged = run_phase(model, hedged=False)
        check_answers("unhedged", unhedged)
        hedged = run_phase(model, hedged=True)
        check_answers("hedged", hedged)
        outcomes = hedged["hedge"].get("outcomes") or {}
        fired = outcomes.get("won", 0) + outcomes.get("lost", 0)
        if fired < 1:
            slow_ms = args.slow_s * 1e3
            bad.append(f"no hedges fired under a {slow_ms:.0f}ms-slow "
                       f"replica: {outcomes}")
        total_shed = (sum((unhedged["shed"].get("shed") or {})
                          .values())
                      + sum((hedged["shed"].get("shed") or {})
                            .values()))
        if total_shed < 1:
            bad.append("the adaptive shed ladder never fired under "
                       "sustained overload")
        if unhedged["p99_s"] is None or hedged["p99_s"] is None:
            bad.append("a phase produced no 200s to measure p99 on")
        elif not (hedged["p99_s"] < unhedged["p99_s"] * 0.8):
            bad.append(f"hedging did not bound p99: hedged "
                       f"{hedged['p99_s'] * 1e3:.1f}ms vs unhedged "
                       f"{unhedged['p99_s'] * 1e3:.1f}ms")

        # graceful drain: in-flight completes, new admissions 503,
        # drain_state reaches 2
        engine = ServingEngine(model, backend="jax", buckets=(1, 2))
        server = ServingServer(engine, max_wait_ms=1.0).start()
        plan = faults.FaultPlan([faults.FaultSpec(
            "batcher.dispatch", kind="latency", latency_s=0.4,
            message="chaos: slow dispatch holds the drain window")],
            seed=3)
        inflight: dict = {}

        def fire_inflight():
            inflight["answer"] = _post(server.url, {"inputs": x},
                                       timeout=30.0)

        try:
            with plan:
                _post(server.url, {"inputs": x}, timeout=60.0)  # warm
                t = threading.Thread(target=fire_inflight,
                                     daemon=True)
                t.start()
                time.sleep(0.1)       # let it into the batcher
                drain_box: dict = {}

                def do_drain():
                    drain_box["drained"] = server.drain(15.0)

                dt = threading.Thread(target=do_drain, daemon=True)
                dt.start()
                time.sleep(0.1)       # drain flag set, still draining
                status, _b, headers = _post(server.url,
                                            {"inputs": x},
                                            timeout=10.0)
                if status != 503 or "Retry-After" not in headers:
                    bad.append(f"admission during drain answered "
                               f"{status} (expected 503 + "
                               f"Retry-After)")
                dt.join(30.0)
                t.join(30.0)
            if inflight.get("answer", (None,))[0] != 200:
                bad.append(f"in-flight request did not complete "
                           f"during drain: "
                           f"{inflight.get('answer', ('hung',))[0]}")
            if not drain_box.get("drained"):
                bad.append("drain timed out with work still queued")
            if REGISTRY.as_dict().get("drain_state") != 2:
                bad.append(f"drain_state gauge "
                           f"{REGISTRY.as_dict().get('drain_state')}"
                           f", expected 2 (drained)")
            print(json.dumps({"phase": "drain",
                              "inflight": inflight.get(
                                  "answer", ("hung",))[0],
                              "drained": drain_box.get("drained")}))
        finally:
            server.stop()
            engine.close()
    print(json.dumps({"scenario": "overload", "ok": not bad,
                      "violations": bad}))
    return 1 if bad else 0


def _zoo_scenario(args) -> int:
    """``--scenario zoo`` — the multi-tenant acceptance
    (docs/serving.md "Multi-tenant model zoo"): three model families
    behind ONE server under a memory budget smaller than their
    combined weights, mixed-tenant traffic with per-model criticality
    classes, one tenant latency-faulted (``zoo.model.mnist``), one
    reloaded mid-burst.  Asserted:

    * zero raw 500s and zero hangs — every answer is 200/429/503/504,
      with ``Retry-After`` on every 429/503;
    * the ``critical`` tenant is never shed and answers only 200s
      while the ``sheddable`` one browns out;
    * the residency LRU actually churned (evictions ≥ 1) and every
      page-in served byte-identical outputs (per-model distinct-output
      counts stay 1, except the deliberately reloaded tenant's 2);
    * page-in p99 is bounded by the compile cost warmup already paid;
    * the mid-burst reload moved ONLY its own model's generation.
    """
    import collections
    import threading

    from ..serving.server import ServingServer
    from ..serving import zoo as zoo_mod
    from ..telemetry.registry import REGISTRY

    bad: list[str] = []
    inputs = {"mnist": [[0.2] * 16], "wine": [[0.1] * 13],
              "kohonen": [[0.3] * 6]}
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        paths = zoo_mod.make_demo_zoo(tmp)
        wine_v2 = os.path.join(tmp, "wine_v2.znn")
        zoo_mod.write_demo_model(wine_v2, "wine", seed=101)
        # one bucket only: byte-identity across eviction/page-in is an
        # assertion here, and different pad buckets legitimately
        # differ in low-order bits (XLA vectorizes batch shapes
        # differently — the PR-7 de-flake); a single bucket removes
        # that axis so any byte drift IS a residency bug
        zoo = zoo_mod.ModelZoo()       # budget installed after warmup
        zoo.add("mnist", paths["mnist"], backend="jax",
                buckets=(1,), criticality="sheddable")
        zoo.add("wine", paths["wine"], backend="jax",
                buckets=(1,), default=True)
        zoo.add("kohonen", paths["kohonen"], backend="jax",
                buckets=(1,), criticality="critical")
        # shed interval 400ms: the slow tenant dispatches one batch
        # per injected fault latency (250ms), and CoDel's "standing"
        # anchor deliberately breaks on a 2-interval sample gap — the
        # interval must comfortably exceed the dispatch cadence or
        # overload can never read as standing
        server = ServingServer(
            zoo=zoo, max_batch=4, max_wait_ms=1.0, max_queue=32,
            default_deadline_ms=10000.0, shed_target_ms=25.0,
            shed_interval_ms=400.0).start()
        # pay every compile up front and TIME it — "page-in p99
        # bounded by warmup" is the claim that re-admitting an evicted
        # model costs device_put milliseconds, not the jit seconds
        # warmup paid once
        t0 = time.monotonic()
        total_bytes = 0
        for entry in zoo.entries():
            entry.engine.warmup((len(inputs[entry.name][0]),))
            total_bytes += entry.engine.weight_nbytes()
        warmup_ms = (time.monotonic() - t0) * 1e3
        # now tighten the screw: the budget holds ~60% of the zoo, so
        # cycling all three tenants HAS to evict
        zoo.memory_budget = int(total_bytes * args.zoo_budget_frac)
        plan = faults.FaultPlan([faults.FaultSpec(
            "zoo.model.mnist", kind="latency",
            latency_s=args.slow_s,
            message="chaos: slow tenant")], seed=13)
        answers = collections.defaultdict(list)  # model -> (code, ra)
        outputs = collections.defaultdict(set)   # model -> bodies seen
        mu = threading.Lock()
        stop = threading.Event()

        def client(model: str):
            while not stop.is_set():
                try:
                    code, body, headers = _post(
                        server.url, {"inputs": inputs[model]},
                        timeout=30.0, headers={"X-Model": model})
                except Exception:
                    code, body, headers = -1, {}, {}
                with mu:
                    answers[model].append(
                        (code, "Retry-After" in headers))
                    if code == 200:
                        outputs[model].add(json.dumps(body["outputs"]))
                stop.wait(0.002)

        threads = [threading.Thread(target=client, args=(m,),
                                    daemon=True)
                   for m in ("mnist",) * 4 + ("wine",) * 2
                   + ("kohonen",) * 2]

        def _shed_critical() -> float:
            snap = REGISTRY.as_dict().get("shed_total", 0)
            return (snap.get("criticality=critical", 0)
                    if isinstance(snap, dict) else 0)

        shed_crit_before = _shed_critical()
        reload_rec: dict = {}
        try:
            with plan:
                for t in threads:
                    t.start()
                # mid-burst: hot-reload ONE tenant while the other two
                # keep serving — isolation is the assertion
                stop.wait(args.duration_s / 3.0)
                status, rec = _admin_reload_named(server.url, "wine",
                                                  wine_v2)
                reload_rec = {"http_status": status, **rec}
                stop.wait(args.duration_s * 2.0 / 3.0)
        finally:
            stop.set()
            for t in threads:
                t.join(30.0)
            zoo_metrics = zoo.metrics()
            shed_state = {e.name: (e.batcher.shedder.metrics()
                                   if e.batcher and e.batcher.shedder
                                   else {})
                          for e in zoo.entries()}
            server.stop()
            zoo.close()
        # -- invariants ---------------------------------------------------
        for model, got in sorted(answers.items()):
            codes = collections.Counter(c for c, _ra in got)
            if codes.get(-1):
                bad.append(f"{model}: {codes[-1]} hung/dropped "
                           f"request(s)")
            raw = {c for c in codes if c not in (200, 429, 503, 504)}
            if raw:
                bad.append(f"{model}: raw failure codes {sorted(raw)}")
            missing_ra = sum(1 for c, ra in got
                             if c in (429, 503) and not ra)
            if missing_ra:
                bad.append(f"{model}: {missing_ra} refusal(s) without "
                           f"Retry-After")
            print(json.dumps({"phase": "burst", "model": model,
                              "codes": dict(codes)}))
        crit = collections.Counter(c for c, _ra in answers["kohonen"])
        if set(crit) != {200}:
            bad.append(f"critical tenant saw non-200 answers: "
                       f"{dict(crit)}")
        shed_crit = _shed_critical() - shed_crit_before
        if shed_crit:
            bad.append(f"critical traffic was shed {shed_crit} "
                       f"time(s) during the drill")
        if not any(sm.get("shed") for sm in shed_state.values()):
            bad.append(f"no tenant ever shed under a "
                       f"{args.slow_s * 1e3:.0f}ms-slow sheddable "
                       f"tenant: {shed_state}")
        evicted = REGISTRY.as_dict().get("model_evictions_total", 0)
        n_evicted = (sum(evicted.values())
                     if isinstance(evicted, dict) else evicted)
        if n_evicted < 1:
            bad.append(f"the residency LRU never evicted under a "
                       f"{zoo.memory_budget}-byte budget "
                       f"(weights total {total_bytes})")
        p99 = zoo_metrics.get("pagein_p99_ms")
        if p99 is None:
            bad.append("no page-ins recorded — the budget never bit")
        elif p99 >= warmup_ms:
            bad.append(f"page-in p99 {p99:.1f}ms not bounded by the "
                       f"warmup compile cost {warmup_ms:.1f}ms — "
                       f"re-admission is paying compiles again")
        if reload_rec.get("http_status") != 200 \
                or (reload_rec.get("last_reload") or {}).get("outcome") \
                != "ok":
            bad.append(f"mid-burst wine reload failed: {reload_rec}")
        gens = {r["model"]: r["generation"]
                for r in zoo_metrics["models"].values()}
        if gens != {"mnist": 1, "wine": 2, "kohonen": 1}:
            bad.append(f"reload isolation violated: generations "
                       f"{gens}, expected wine=2 and others=1")
        if len(outputs["mnist"]) != 1 or len(outputs["kohonen"]) != 1:
            bad.append(f"eviction/page-in changed answer bytes: "
                       f"mnist {len(outputs['mnist'])} distinct, "
                       f"kohonen {len(outputs['kohonen'])}")
        if len(outputs["wine"]) != 2:
            bad.append(f"wine should have exactly 2 distinct outputs "
                       f"(pre/post reload), saw "
                       f"{len(outputs['wine'])}")
        print(json.dumps({
            "scenario": "zoo", "ok": not bad, "violations": bad,
            "warmup_ms": round(warmup_ms, 1),
            "pagein_p99_ms": p99, "evictions": n_evicted,
            "shed": {m: s.get("shed") for m, s in shed_state.items()},
            "reload": reload_rec.get("http_status"),
            "generations": gens}))
    return 1 if bad else 0


def _san_scenario(args) -> int:
    """``--scenario san`` — the zoo drill, sanitized
    (tools/san_smoke.sh): enable the zsan runtime layer
    (:mod:`znicz_tpu.sanitizer`) and re-run the full multi-tenant
    ``zoo`` scenario under it.  Every lock the drill's server / zoo /
    engines / batchers create is a tracked wrapper; the observed
    acquisition graph is printed at the end.  Asserted:

    * the zoo drill itself still passes (the sanitizer must not change
      behaviour, only watch it);
    * ZERO lock-order inversions across the whole drill — client
      bursts, budget evictions, the latency fault, the mid-burst
      reload and the page-in observer all interleave, so a cycle in
      the real lock web has every chance to show up here;
    * the acquisition graph is non-trivial (edges were actually
      observed — a zero-edge run means the instrumentation fell off,
      not that the code is clean).

    Long holds are reported but not fatal: the drill deliberately
    pays cold jit compiles under the generation lock.
    """
    from .. import sanitizer

    if sanitizer.enabled():
        # ZNICZ_SAN=1 got there first: ride the existing state
        sanitizer.reset()
        rc = _zoo_scenario(args)
        rep = sanitizer.report()
    else:
        sanitizer.enable()
        try:
            rc = _zoo_scenario(args)
        finally:
            rep = sanitizer.disable()
    bad = []
    if rc != 0:
        bad.append(f"sanitized zoo drill failed (rc {rc})")
    if rep["inversions"]:
        bad.append(f"{len(rep['inversions'])} lock-order "
                   f"inversion(s) observed")
    if rep["edges"] == 0:
        bad.append("no acquisition edges observed — sanitizer "
                   "instrumentation is not engaged")
    print(sanitizer.format_report(rep))
    print(json.dumps({
        "scenario": "san", "ok": not bad, "violations": bad,
        "acquires": rep["acquires"], "edges": rep["edges"],
        "inversions": len(rep["inversions"]),
        "long_holds": len(rep["long_holds"])}))
    return 1 if bad else 0


def _wire_scenario(args) -> int:
    """``--scenario wire`` — the request-path wire-protocol acceptance
    (docs/serving.md "Wire protocol"): ONE server serving the demo
    model int8-quantized with response memoization on, driven by
    concurrent JSON and binary (``application/x-znicz-tensor``)
    keep-alive clients plus a malformed-binary attacker, while a
    transient ``engine.forward`` fault trips the breaker mid-burst.
    Asserted:

    * zero raw 500s and zero hangs on BOTH wire formats — every
      answer is 200/429/503/504, with ``Retry-After`` on refusals;
    * every malformed binary body answers 400 FAST (bounded p99) —
      a junk header must never wedge a handler or leak a 500;
    * post-recovery, one fresh input posted through both formats
      decodes to exactly equal outputs, and the JSON bytes are
      byte-identical to the reference ``json.dumps`` encoding;
    * memoization HIT during the burst (the fixed payload repeats),
      and a hot reload swaps the key space — the same input misses
      once under the new generation, then hits again;
    * the int8 path stays active throughout (verified at load, zero
      fallbacks counted).
    """
    import collections
    import http.client as http_client
    import threading

    from ..serving import wire as wire_mod
    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    bad: list[str] = []
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        model = os.path.join(tmp, "demo.znn")
        _write_demo_znn(model)
        engine = ServingEngine(
            model, backend="jax", buckets=(1, 2, 8), quantize="int8",
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                              max_delay_s=0.05),
            breaker=CircuitBreaker(failure_threshold=2,
                                   cooldown_s=0.5))
        if not engine.quantized_active():
            bad.append("int8 build fell back on the demo model at "
                       "load — nothing quantized is being drilled")
        server = ServingServer(engine, max_wait_ms=1.0,
                               memo_entries=64).start()
        x = np.asarray([[0.1, -0.2, 0.3, 0.4]], np.float32)
        fixed_json = json.dumps({"inputs": x.tolist()}).encode()
        fixed_bin = wire_mod.encode_tensor(x)
        good_bin = wire_mod.encode_tensor(x)
        junk_bodies = [good_bin[:5],                  # short header
                       b"JUNKJUNKJUNKJUNK",           # bad magic
                       good_bin[:-2],                 # truncated payload
                       good_bin + b"\x00"]            # trailing junk

        def unique_x(i: int) -> np.ndarray:
            ux = x.copy()
            ux[0, 0] = 0.1 + (i % 997) * 1e-3
            return ux

        def json_body(i: int) -> bytes:
            # every other request repeats the fixed payload (memo
            # exercise); the rest are unique and MUST reach the
            # engine, where the fault plan is waiting
            if i % 2 == 0:
                return fixed_json
            return json.dumps({"inputs": unique_x(i).tolist()}).encode()

        def bin_body(i: int) -> bytes:
            if i % 2 == 0:
                return fixed_bin
            return wire_mod.encode_tensor(unique_x(i))

        lanes = {
            "json": (json_body, {"Content-Type": "application/json"}),
            "binary": (bin_body,
                       {"Content-Type": wire_mod.CONTENT_TYPE,
                        "Accept": wire_mod.CONTENT_TYPE}),
            "junk": (lambda i: junk_bodies[i % len(junk_bodies)],
                     {"Content-Type": wire_mod.CONTENT_TYPE}),
        }
        answers = collections.defaultdict(list)  # lane -> (code, ms, ra)
        mu = threading.Lock()
        stop = threading.Event()

        def client(lane_name: str):
            body_fn, headers = lanes[lane_name]
            conn = http_client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            i = 0
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    conn.request("POST", "/predict", body_fn(i),
                                 headers)
                    r = conn.getresponse()
                    r.read()
                    code, ra = r.status, bool(
                        r.getheader("Retry-After"))
                except Exception:
                    conn.close()
                    conn = http_client.HTTPConnection(
                        "127.0.0.1", server.port, timeout=30)
                    code, ra = -1, False
                ms = (time.monotonic() - t0) * 1e3
                with mu:
                    answers[lane_name].append((code, ms, ra))
                i += 1
                stop.wait(0.002)
            conn.close()

        # transient device fault mid-burst: enough firings to trip the
        # breaker through the retries, then exhausted — the drill must
        # see open, degraded AND recovered serving under binary load
        plan = faults.FaultPlan([faults.FaultSpec(
            "engine.forward", after=20, times=8,
            message="chaos: injected transient device fault")],
            seed=11)
        threads = [threading.Thread(target=client, args=(ln,),
                                    daemon=True)
                   for ln in ("json", "json", "binary", "binary",
                              "junk")]
        try:
            with plan:
                for t in threads:
                    t.start()
                stop.wait(args.duration_s)
        finally:
            stop.set()
            for t in threads:
                t.join(30.0)
        # -- invariants (cleanup guaranteed: an unexpected raise in
        # the checks must not leak the server's threads) ------------------
        try:
            bad, summary = _wire_invariants(bad, answers, server,
                                            engine, model, x, wire_mod)
        finally:
            server.stop()
            engine.close()
        print(json.dumps(summary))
    return 1 if bad else 0


def _wire_invariants(bad, answers, server, engine, model, x,
                     wire_mod):
    """The wire scenario's post-burst assertions (split out so the
    caller can guarantee server/engine teardown around them)."""
    import collections

    for lane_name in ("json", "binary"):
        got = answers[lane_name]
        codes = collections.Counter(c for c, _ms, _ra in got)
        if codes.get(-1):
            bad.append(f"{lane_name}: {codes[-1]} hung/dropped "
                       f"request(s)")
        raw = {c for c in codes if c not in (200, 429, 503, 504)}
        if raw:
            bad.append(f"{lane_name}: raw failure codes "
                       f"{sorted(raw)}")
        missing_ra = sum(1 for c, _ms, ra in got
                         if c in (429, 503) and not ra)
        if missing_ra:
            bad.append(f"{lane_name}: {missing_ra} refusal(s) "
                       f"without Retry-After")
        print(json.dumps({"phase": "burst", "lane": lane_name,
                          "codes": dict(codes)}))
    junk_codes = collections.Counter(
        c for c, _ms, _ra in answers["junk"])
    if set(junk_codes) != {400}:
        bad.append(f"malformed binary must answer 400 and only "
                   f"400, saw {dict(junk_codes)}")
    junk_ms = sorted(ms for _c, ms, _ra in answers["junk"])
    junk_p99 = (junk_ms[min(len(junk_ms) - 1,
                            int(len(junk_ms) * 0.99))]
                if junk_ms else None)
    if junk_p99 is None or junk_p99 > 2000.0:
        bad.append(f"malformed-binary p99 {junk_p99}ms — a junk "
                   f"header is hanging the handler")
    # recovery + deterministic cross-format parity on fresh input
    time.sleep(0.7)
    probe = np.asarray([[0.05, 0.1, -0.15, 0.2]], np.float32)
    code_j, body_j, _ = _post(server.url,
                              {"inputs": probe.tolist()})
    req = urllib.request.Request(
        server.url + "predict", wire_mod.encode_tensor(probe),
        {"Content-Type": wire_mod.CONTENT_TYPE,
         "Accept": wire_mod.CONTENT_TYPE})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            code_b, raw_b = r.status, r.read()
    except urllib.error.HTTPError as e:
        # a non-200 must become the violation it is, not an
        # unhandled traceback (the JSON probe's _post helper
        # already eats HTTPError the same way)
        code_b, raw_b = e.code, e.read()
    if code_j != 200 or code_b != 200:
        bad.append(f"post-recovery probes not 200: json={code_j} "
                   f"binary={code_b}")
    else:
        y_json = np.asarray(body_j["outputs"], np.float32)
        y_bin = wire_mod.decode_tensor(raw_b)
        if not np.array_equal(y_json, y_bin):
            bad.append("post-recovery JSON and binary outputs "
                       "disagree")
    # memoization: the fixed payload must have HIT during the
    # burst, and a reload must swap the key space (miss then hit)
    cache = server.zoo.resolve().response_cache
    m0 = cache.metrics()
    if m0["hits"] < 1:
        bad.append(f"response cache never hit under repeat "
                   f"traffic: {m0}")
    rec = engine.reload(model)
    if rec["outcome"] != "ok":
        bad.append(f"post-burst reload failed: {rec}")
    _post(server.url, {"inputs": x.tolist()})
    m1 = cache.metrics()
    if m1["misses"] != m0["misses"] + 1:
        bad.append(f"reload did not swap the memo key space: "
                   f"misses {m0['misses']} -> {m1['misses']}")
    _post(server.url, {"inputs": x.tolist()})
    m2 = cache.metrics()
    if m2["hits"] != m1["hits"] + 1:
        bad.append(f"repeat under the new generation did not hit: "
                   f"hits {m1['hits']} -> {m2['hits']}")
    em = engine.metrics()
    if not em.get("quantized"):
        bad.append(f"int8 serving fell back during the drill "
                   f"(fallbacks={em.get('quantize_fallbacks')})")
    summary = {"scenario": "wire", "ok": not bad,
               "violations": bad,
               "junk_p99_ms": (round(junk_p99, 1)
                               if junk_p99 is not None else None),
               "memo": m2, "breaker": engine.breaker.metrics(),
               "quantized": em.get("quantized"),
               "generation": engine.generation}
    return bad, summary


def _slo_scenario(args) -> int:
    """``--scenario slo`` — the burn-rate observability acceptance
    (docs/observability.md "SLO engine"): two tenants behind one
    server, each with a latency SLO judged by a live
    :class:`~znicz_tpu.telemetry.sloengine.SLOEngine` on sub-second
    windows; the ``sheddable`` tenant is latency-faulted at its
    ``zoo.model.<name>`` site while the ``critical`` tenant stays
    quiet.  Asserted:

    * the faulted tenant's fast-window burn rate crosses the alert
      threshold and EXACTLY ONE alert fires for it — none for the
      healthy tenant, whose error budget stays intact;
    * ``GET /alertz`` reports the firing alert live, ``/statusz``
      renders the SLO section, and the alert transition landed in the
      flight recorder;
    * zero raw 500s and zero hangs — a latency regression must burn
      the budget, not the degradation contract;
    * per-tenant cost attribution: the sum of
      ``model_device_ms_total`` across tenants is within 10% of the
      total device time the engines measured (the chip bill adds up).
    """
    import collections
    import threading

    from ..serving.server import ServingServer
    from ..serving import zoo as zoo_mod
    from ..telemetry import sloengine
    from ..telemetry.flightrecorder import RECORDER
    from ..telemetry.registry import REGISTRY

    bad: list[str] = []
    inputs = {"mnist": [[0.2] * 16], "wine": [[0.1] * 13]}

    def _labeled(name: str) -> dict:
        snap = REGISTRY.as_dict().get(name, 0)
        return snap if isinstance(snap, dict) else {}

    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        paths = zoo_mod.make_demo_zoo(tmp, families=("mnist", "wine"))
        zoo = zoo_mod.ModelZoo()
        zoo.add("mnist", paths["mnist"], backend="jax",
                buckets=(1, 2, 4), criticality="sheddable")
        zoo.add("wine", paths["wine"], backend="jax",
                buckets=(1, 2, 4), criticality="critical",
                default=True)
        # no shed ladder and no deadlines: the drill's contract is
        # that a latency regression burns the BUDGET, with every
        # answer still a 200 — refusals would be a different drill
        server = ServingServer(zoo=zoo, max_batch=4, max_wait_ms=1.0,
                               max_queue=64).start()
        for entry in zoo.entries():
            entry.engine.warmup((len(inputs[entry.name][0]),))
        fast_s, slow_s = args.slo_fast_s, 3.0 * args.slo_fast_s
        specs = [sloengine.SLOSpec(
            name="latency", model=m, objective="latency",
            threshold_ms=args.slo_threshold_ms, target=0.9,
            fast_window_s=fast_s, slow_window_s=slow_s,
            burn_threshold=args.slo_burn, min_events=5,
            budget_window_s=10.0 * slow_s, severity="page")
            for m in ("mnist", "wine")]
        slo = sloengine.SLOEngine.for_server(
            server, specs, interval_s=max(0.05, fast_s / 5.0))
        server.attach_slo(slo)
        slo.start()
        alerts_before = dict(_labeled("slo_alerts_total"))
        plan = faults.FaultPlan([faults.FaultSpec(
            "zoo.model.mnist", kind="latency", latency_s=args.slow_s,
            message="chaos: slow tenant burning its latency SLO")],
            seed=17)
        answers = collections.defaultdict(list)
        mu = threading.Lock()
        stop = threading.Event()

        def client(model: str):
            while not stop.is_set():
                try:
                    code, _body, _h = _post(
                        server.url, {"inputs": inputs[model]},
                        timeout=30.0, headers={"X-Model": model})
                except Exception:
                    code = -1          # hang / dropped conn = failure
                with mu:
                    answers[model].append(code)
                stop.wait(0.002)

        threads = [threading.Thread(target=client, args=(m,),
                                    daemon=True)
                   for m in ("mnist",) * 3 + ("wine",) * 2]
        alertz_mid: dict = {}
        try:
            with plan:
                for t in threads:
                    t.start()
                stop.wait(args.duration_s * 0.7)
                # mid-burst, fault still live: the alert must already
                # be visible on the live surface
                with urllib.request.urlopen(server.url + "alertz",
                                            timeout=10.0) as r:
                    alertz_mid = json.loads(r.read())
                with urllib.request.urlopen(server.url + "statusz",
                                            timeout=10.0) as r:
                    statusz_text = r.read().decode()
                stop.wait(args.duration_s * 0.3)
                # one final deterministic evaluation before the fault
                # plan lifts (the loop's own cadence keeps running
                # underneath; tick() is just a judged snapshot)
                slo.tick()
        finally:
            stop.set()
            for t in threads:
                t.join(30.0)
            slo.stop()
            status = slo.status()
            server.stop()
            zoo.close()
        # -- invariants ---------------------------------------------------
        for model, got in sorted(answers.items()):
            codes = collections.Counter(got)
            if codes.get(-1):
                bad.append(f"{model}: {codes[-1]} hung/dropped "
                           f"request(s)")
            raw = {c for c in codes if c not in (200, 429, 503, 504)}
            if raw:
                bad.append(f"{model}: raw failure codes {sorted(raw)}")
            if codes.get(500):
                bad.append(f"{model}: {codes[500]} raw 500(s)")
            print(json.dumps({"phase": "burst", "model": model,
                              "codes": dict(codes)}))
        rows = {(r["slo"], r["model"]): r for r in status["slos"]}
        hot = rows[("latency", "mnist")]
        quiet = rows[("latency", "wine")]
        if hot["burn_fast"] < args.slo_burn:
            bad.append(f"faulted tenant's fast-window burn "
                       f"{hot['burn_fast']} never crossed the "
                       f"{args.slo_burn} threshold")
        if not hot["firing"]:
            bad.append("faulted tenant's alert is not firing at the "
                       "end of the faulted burst")
        alerts_after = _labeled("slo_alerts_total")
        fired = {k: v - alerts_before.get(k, 0)
                 for k, v in alerts_after.items()
                 if v - alerts_before.get(k, 0)}
        mnist_key = "model=mnist,severity=page,slo=latency"
        wine_fired = sum(v for k, v in fired.items() if "model=wine" in k)
        if fired.get(mnist_key) != 1:
            bad.append(f"expected exactly one alert firing for the "
                       f"faulted tenant, saw {fired}")
        if wine_fired:
            bad.append(f"the healthy tenant fired {wine_fired} "
                       f"alert(s)")
        if quiet["budget_remaining"] < 0.9:
            bad.append(f"healthy tenant's budget eroded to "
                       f"{quiet['budget_remaining']} under someone "
                       f"else's fault")
        if quiet["firing"]:
            bad.append("healthy tenant's alert is firing")
        if not alertz_mid.get("enabled") \
                or not any(a["model"] == "mnist"
                           for a in alertz_mid.get("alerts", [])):
            bad.append(f"GET /alertz did not report the firing alert "
                       f"mid-burst: {alertz_mid}")
        if "slo burn rates" not in statusz_text:
            bad.append("/statusz has no SLO section")
        # a firing alert lands in the ERROR ring too (outcome !=
        # "ok") — check there: a busy burst legitimately flushes the
        # recent ring, which is exactly why the error ring exists
        snap = RECORDER.snapshot()
        recorded = [r for r in snap["errors"] + snap["recent"]
                    if r.get("kind") == "slo_alert"
                    and r.get("model") == "mnist"
                    and r.get("transition") == "fire"]
        if not recorded:
            bad.append("the alert transition never reached the "
                       "flight recorder")
        # cost attribution: the per-tenant ledger must add up to what
        # the engines measured (within 10%, per the acceptance)
        attributed = sum(_labeled("model_device_ms_total").values())
        measured = sum(e.engine.device_ms_total()
                       for e in zoo.entries())
        if measured <= 0:
            bad.append("engines measured zero device time under a "
                       "multi-second burst")
        elif abs(attributed - measured) > 0.1 * measured:
            bad.append(f"per-tenant device-ms attribution "
                       f"({attributed:.1f}) is not within 10% of the "
                       f"measured engine device time "
                       f"({measured:.1f})")
        print(json.dumps({
            "scenario": "slo", "ok": not bad, "violations": bad,
            "hot": {k: hot[k] for k in ("burn_fast", "burn_slow",
                                        "budget_remaining", "firing")},
            "quiet": {k: quiet[k] for k in ("burn_fast", "burn_slow",
                                            "budget_remaining",
                                            "firing")},
            "alerts_fired": fired,
            "device_ms": {"attributed": round(attributed, 1),
                          "measured": round(measured, 1)}}))
    return 1 if bad else 0


def _write_poison_znn(path: str, fin: int = 4, hidden: int = 3,
                      classes: int = 2) -> None:
    """A deliberately regressed candidate that the engine's
    zeros-batch reload canary CANNOT catch: the saturated first layer
    maps an all-zeros canary batch to zeros (finite logits), while any
    real input whose elements sum away from zero saturates tanh to
    ±1 and the ±3e38 second-layer weights overflow the logit
    accumulation to inf − inf = NaN — the serving front answers those
    as 500s, which is exactly the live-traffic-only regression the
    fleet walk's burn-rate judgment must roll back."""
    from ..export import ACT, KIND, _commit_znn, _pack_layer, \
        _write_header
    w1 = np.full((fin, hidden), 100.0, np.float32)
    b1 = np.zeros(hidden, np.float32)
    w2 = np.stack([np.full(hidden, 3e38, np.float32),
                   np.full(hidden, -3e38, np.float32)] * (classes // 2),
                  axis=1)
    with open(path + ".tmp", "wb") as fh:
        _write_header(fh, 3)
        _pack_layer(fh, KIND["fc"], ACT["tanh"], [fin, hidden], w1, b1)
        _pack_layer(fh, KIND["fc"], ACT["linear"], [hidden, classes],
                    w2)
        _pack_layer(fh, KIND["softmax"], 0, [])
    _commit_znn(path)


def _fleet_scenario(args) -> int:
    """``--scenario fleet`` — the fleet-fabric acceptance
    (docs/fleet.md): three REAL ``serve`` processes behind a REAL
    ``route`` process; one backend SIGKILLed mid-burst (zero raw
    500s, zero hangs — ejection + failover, Retry-After'd 503s only
    for lost capacity) then restarted (re-admission observed); one
    rolling promotion walked to completion (every backend on the new
    generation, byte-identical post-roll outputs) and one
    deliberately regressed candidate rolled back FLEET-WIDE by the
    mid-walk burn-rate judgment before the walk completes."""
    import collections
    import shutil
    import signal
    import socket
    import subprocess
    import sys
    import threading

    from ..fleet.rollout import FleetTarget
    from ..promotion import (DirectorySource, PromotionController,
                             SLOPolicy)
    from ..promotion.slo import BurnRatePolicy
    from ..serving import wire as wire_mod

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    n_backends = 3
    tmp = tempfile.mkdtemp(prefix="znicz_chaos_fleet_")
    procs: dict[int, subprocess.Popen] = {}
    router_proc = None

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def boot_backend(port: int, model: str) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "serve",
             "--model", model, "--port", str(port),
             "--max-wait-ms", "1", "--warmup-shape", "4"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    def wait_healthz(url: str, proc, what: str,
                     tries: int = 240) -> bool:
        for _ in range(tries):
            try:
                with urllib.request.urlopen(url + "healthz",
                                            timeout=2) as r:
                    json.loads(r.read())
                return True
            except Exception:
                if proc is not None and proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    bad.append(f"{what} exited rc={proc.returncode}: "
                               f"{out[-300:]}")
                    return False
                time.sleep(0.25)
        bad.append(f"{what} never answered /healthz")
        return False

    def router_health() -> dict:
        with urllib.request.urlopen(router_url + "healthz",
                                    timeout=10) as r:
            return json.loads(r.read())

    try:
        v1 = os.path.join(tmp, "v1.znn")
        _write_demo_znn(v1, seed=5)
        ports = [free_port() for _ in range(n_backends)]
        rport = free_port()
        backend_urls = [f"http://127.0.0.1:{p}/" for p in ports]
        router_url = f"http://127.0.0.1:{rport}/"
        for i, port in enumerate(ports):
            procs[i] = boot_backend(port, v1)
        for i, port in enumerate(ports):
            if not wait_healthz(backend_urls[i], procs[i],
                                f"backend {i}"):
                return 1
        router_proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "route",
             "--port", str(rport), "--probe-interval-s", "0.3",
             "--breaker-threshold", "2",
             "--breaker-cooldown-s", "1.0"]
            + [f for i, u in enumerate(backend_urls)
               for f in ("--backend", f"{u},name=b{i}")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        if not wait_healthz(router_url, router_proc, "router"):
            return 1

        # ---- phase 1: SIGKILL one backend mid-burst, then restart it
        answers: list[tuple] = []       # (code, retry_after_present)
        mu = threading.Lock()
        stop = threading.Event()
        bin_body = wire_mod.encode_tensor(np.asarray(x, np.float32))

        def client(ci: int):
            # every other client drives the binary pass-through leg —
            # the router must route both formats identically
            binary = ci % 2 == 1
            n = 0
            while not stop.is_set():
                try:
                    if binary:
                        req = urllib.request.Request(
                            router_url + "predict", bin_body,
                            {"Content-Type": wire_mod.CONTENT_TYPE,
                             "Accept": wire_mod.CONTENT_TYPE})
                        with urllib.request.urlopen(req,
                                                    timeout=15) as r:
                            r.read()
                            code, headers = r.status, dict(r.headers)
                    else:
                        code, _body, headers = _post(
                            router_url, {"inputs": x}, timeout=15)
                except urllib.error.HTTPError as e:
                    code, headers = e.code, dict(e.headers)
                    e.read()
                except Exception:
                    code, headers = -1, {}    # hang/conn error = bad
                with mu:
                    answers.append((code,
                                    "Retry-After" in headers))
                n += 1
                stop.wait(0.005)

        threads = [threading.Thread(target=client, args=(ci,),
                                    daemon=True) for ci in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        procs[1].kill()                 # SIGKILL, not a drain: the
        procs[1].wait(timeout=15)       # fabric must absorb a CRASH
        # ejection: poll until the router reports b1 out of rotation
        ejected = False
        for _ in range(40):
            rows = {r["name"]: r for r in router_health()["backends"]}
            if rows["b1"]["breaker"]["state"] == "open":
                ejected = True
                break
            time.sleep(0.25)
        if not ejected:
            bad.append("killed backend b1 was never ejected (breaker "
                       "never opened at the router)")
        time.sleep(1.0)
        # restart on the same port: the fabric must RE-admit it
        procs[1] = boot_backend(ports[1], v1)
        wait_healthz(backend_urls[1], procs[1], "restarted backend 1")
        readmitted = False
        for _ in range(60):
            rows = {r["name"]: r for r in router_health()["backends"]}
            if rows["b1"]["breaker"]["state"] == "closed":
                readmitted = True
                break
            time.sleep(0.25)
        if not readmitted:
            bad.append("restarted backend b1 was never re-admitted")
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(20.0)
        codes = collections.Counter(code for code, _ra in answers)
        print(json.dumps({"phase": "kill-burst",
                          "codes": dict(sorted(codes.items())),
                          "ejected": ejected,
                          "readmitted": readmitted}))
        if codes.get(-1):
            bad.append(f"{codes[-1]} request(s) hung or died on a "
                       f"connection error during the kill burst")
        if codes.get(500):
            bad.append(f"{codes[500]} raw 500(s) during the kill "
                       f"burst")
        for code, ra in answers:
            if code in (429, 503) and not ra:
                bad.append(f"a {code} refusal carried no Retry-After")
                break
        # traffic reaches the re-admitted backend again
        seen = set()
        for _ in range(30):
            code, _b, headers = _post(router_url, {"inputs": x},
                                      timeout=15)
            seen.add(headers.get("X-Fleet-Backend"))
        if "b1" not in seen:
            bad.append(f"re-admitted backend b1 got no traffic "
                       f"(answering backends: {sorted(seen)})")

        # ---- phase 2 + 3: promote-one-then-fleet, then a regressed
        # candidate rolled back fleet-wide mid-walk.  The controller
        # runs in THIS process; every reload/weight/metrics call is a
        # real HTTP hop to the subprocesses.
        cands = os.path.join(tmp, "cands")
        deploy = os.path.join(tmp, "deploy")
        os.makedirs(cands)
        stop = threading.Event()
        answers = []
        threads = [threading.Thread(target=client, args=(ci,),
                                    daemon=True) for ci in range(4)]
        for t in threads:
            t.start()

        def make_controller(canary_weight: float):
            walk_policy = BurnRatePolicy(
                objective="availability", target=0.99,
                window_s=60.0, probe_interval_s=0.1,
                fast_window_s=0.6, max_burn_rate=2.0, min_samples=5)
            target = FleetTarget(
                backend_urls, router_url=router_url,
                canary_weight=canary_weight,
                walk_policy=walk_policy, settle_s=1.0,
                probe_interval_s=0.1)
            return PromotionController(
                DirectorySource(cands), target, deploy_dir=deploy,
                policy=SLOPolicy(window_s=1.0, probe_interval_s=0.25,
                                 min_samples=3, max_p99_ms=5000.0,
                                 max_error_rate=0.5),
                poll_interval_s=0.05,
                ledger=os.path.join(deploy, "promotions.jsonl"))

        time.sleep(0.5)
        v2 = os.path.join(cands, "v2.znn")
        _write_demo_znn(v2, seed=23)
        outcome = make_controller(canary_weight=0.25).run_once()
        print(json.dumps({"phase": "rolling-promotion",
                          "outcome": outcome}))
        if outcome != "promoted":
            bad.append(f"rolling promotion concluded {outcome!r}, "
                       f"expected 'promoted'")
        stop.set()
        for t in threads:
            t.join(20.0)
        clean = collections.Counter(c for c, _ra in answers)
        if clean.get(-1):
            bad.append(f"{clean[-1]} request(s) hung during the "
                       f"clean rolling promotion")
        if clean.get(500):
            bad.append("raw 500(s) during the CLEAN rolling "
                       "promotion — the walk broke live traffic")
        # byte-compares run QUIESCED (traffic stopped, in-flight
        # batches drained): live coalescing can pad the probe into a
        # different bucket whose executable differs in low-order bits
        # — the PR 7 lesson, re-learned at fleet scale
        time.sleep(0.5)
        gens, outs = [], []
        for url in backend_urls:
            code, body, _h = _post(url, {"inputs": x}, timeout=15)
            outs.append((code, json.dumps(body, sort_keys=True)))
            with urllib.request.urlopen(url + "healthz",
                                        timeout=10) as r:
                gens.append(json.loads(r.read())["model_generation"])
        if any(g != gens[0] or g < 2 for g in gens):
            bad.append(f"post-roll generations diverge: {gens}")
        if len(set(outs)) != 1 or outs[0][0] != 200:
            bad.append(f"post-roll outputs are not byte-identical "
                       f"200s across the fleet: {outs}")
        v2_answer = outs[0]

        # the regressed candidate: dark canary (weight 0 during the
        # watch — no router traffic reaches it, so the min-samples
        # gate passes it to the WALK, which is the judgment under
        # test), then the walk's fleet-aggregated burn rate must
        # catch the 500s and roll every backend back
        stop = threading.Event()
        answers = []
        threads = [threading.Thread(target=client, args=(ci,),
                                    daemon=True) for ci in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        v3 = os.path.join(cands, "v3.znn")
        _write_poison_znn(v3)
        outcome = make_controller(canary_weight=0.0).run_once()
        print(json.dumps({"phase": "regressed-candidate",
                          "outcome": outcome}))
        if outcome != "rolled_back":
            bad.append(f"regressed candidate concluded {outcome!r}, "
                       f"expected 'rolled_back'")
        walk_rec = None
        with open(os.path.join(deploy, "promotions.jsonl")) as fh:
            for line in fh:
                rec = json.loads(line)
                if rec.get("event") == "fleet_rollback":
                    walk_rec = rec
        if walk_rec is None:
            bad.append("no fleet_rollback event in the ledger")
        elif not walk_rec.get("walked") \
                or walk_rec["walked"] >= n_backends:
            bad.append(f"fleet rollback fired at walked="
                       f"{walk_rec.get('walked')}, expected mid-walk "
                       f"(1..{n_backends - 1})")
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(20.0)
        regress = collections.Counter(c for c, _ra in answers)
        print(json.dumps({"phase": "regression-traffic",
                          "codes": dict(sorted(regress.items()))}))
        if regress.get(-1):
            bad.append(f"{regress[-1]} request(s) hung during the "
                       f"regressed-candidate phase")
        if not regress.get(500):
            bad.append("the regressed candidate never produced a "
                       "500 — the rollback rolled back nothing "
                       "observable")
        # post-rollback, quiesced: the whole fleet answers v2's
        # exact bytes
        time.sleep(0.5)
        for url in backend_urls:
            code, body, _h = _post(url, {"inputs": x}, timeout=15)
            if (code, json.dumps(body, sort_keys=True)) != v2_answer:
                bad.append(f"post-rollback answer on {url} is not "
                           f"byte-identical to v2's")
        print(json.dumps({"scenario": "fleet", "ok": not bad,
                          "violations": bad}))
        return 1 if bad else 0
    finally:
        if router_proc is not None:
            router_proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 15.0
        for proc in [router_proc] + list(procs.values()):
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1,
                                      deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _placement_scenario(args) -> int:
    """``--scenario placement`` — the placement-fabric acceptance
    (docs/fleet.md "Placement"): three REAL multi-tenant ``serve``
    processes (the demo zoo on each) behind a REAL ``route
    --placement 1`` process.  Asserted:

    * the router discovers every tenant and places each on exactly
      ``replication`` backends; steady-state traffic routes INSIDE
      the placement set (``X-Fleet-Placement: placed``, answering
      backend ∈ the tenant's set);
    * fleet-wide resident bytes stay ≤ (1 + replication) × one zoo's
      total weight bytes — the hint push releases non-placed copies,
      so the footprint is ~replication ×, not N × (the slack is one
      in-transition copy);
    * SIGKILLing the backend that owns the hot tenant mid-burst
      yields ZERO raw 500s and zero hangs (degraded routing bridges
      the gap) and the map HEALS: the next discovery sweep re-places
      the tenant on live backends only;
    * the healed tenant keeps answering 200s, and the footprint bound
      still holds afterwards.
    """
    import collections
    import shutil
    import signal
    import socket
    import subprocess
    import sys
    import threading

    from ..serving import zoo as zoo_mod

    bad: list[str] = []
    inputs = {"mnist": [[0.2] * 16], "wine": [[0.1] * 13],
              "kohonen": [[0.3] * 6]}
    n_backends = 3
    replication = 1
    tmp = tempfile.mkdtemp(prefix="znicz_chaos_place_")
    procs: dict[int, subprocess.Popen] = {}
    router_proc = None

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def boot_backend(port: int, zoo_dir: str) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "serve",
             "--zoo", zoo_dir, "--port", str(port),
             "--max-wait-ms", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    def wait_healthz(url: str, proc, what: str,
                     tries: int = 240) -> bool:
        for _ in range(tries):
            try:
                with urllib.request.urlopen(url + "healthz",
                                            timeout=2) as r:
                    json.loads(r.read())
                return True
            except Exception:
                if proc is not None and proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    bad.append(f"{what} exited rc={proc.returncode}: "
                               f"{out[-300:]}")
                    return False
                time.sleep(0.25)
        bad.append(f"{what} never answered /healthz")
        return False

    def router_health() -> dict:
        with urllib.request.urlopen(router_url + "healthz",
                                    timeout=10) as r:
            return json.loads(r.read())

    def assignments() -> dict:
        return (router_health().get("placement") or {}) \
            .get("assignments") or {}

    def fleet_footprint() -> tuple[int, int]:
        """(fleet resident bytes, one zoo's total weight bytes) from
        the live backends' /healthz."""
        resident = 0
        zoo_total = 0
        for i, url in enumerate(backend_urls):
            if procs[i].poll() is not None:
                continue
            try:
                with urllib.request.urlopen(url + "healthz",
                                            timeout=10) as r:
                    snap = json.loads(r.read())
            except Exception:
                continue
            resident += int(snap.get("resident_bytes") or 0)
            total = sum(int(row.get("weight_bytes") or 0)
                        for row in snap.get("models") or [])
            zoo_total = max(zoo_total, total)
        return resident, zoo_total

    try:
        zoo_dir = os.path.join(tmp, "zoo")
        os.makedirs(zoo_dir)
        zoo_mod.make_demo_zoo(zoo_dir)
        ports = [free_port() for _ in range(n_backends)]
        rport = free_port()
        backend_urls = [f"http://127.0.0.1:{p}/" for p in ports]
        router_url = f"http://127.0.0.1:{rport}/"
        for i, port in enumerate(ports):
            procs[i] = boot_backend(port, zoo_dir)
        for i, port in enumerate(ports):
            if not wait_healthz(backend_urls[i], procs[i],
                                f"backend {i}"):
                return 1
        router_proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "route",
             "--port", str(rport), "--placement", str(replication),
             "--probe-interval-s", "0.3",
             "--breaker-threshold", "2",
             "--breaker-cooldown-s", "1.0"]
            + [f for i, u in enumerate(backend_urls)
               for f in ("--backend", f"{u},name=b{i}")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        if not wait_healthz(router_url, router_proc, "router"):
            return 1

        # ---- phase 1: the map covers every tenant at `replication`
        amap: dict = {}
        for _ in range(80):
            amap = assignments()
            if set(amap) >= set(inputs) \
                    and all(len(v) == replication
                            for v in amap.values()):
                break
            time.sleep(0.25)
        print(json.dumps({"phase": "discovery", "assignments": amap}))
        if set(amap) < set(inputs):
            bad.append(f"placement never covered the zoo: {amap}")
            return 1

        # steady-state: every tenant answers, routed INSIDE its set
        modes = collections.Counter()
        for _round in range(10):
            for model in inputs:
                code, _b, headers = _post(
                    router_url, {"inputs": inputs[model]},
                    timeout=30, headers={"X-Model": model})
                if code != 200:
                    bad.append(f"steady-state {model} answered {code}")
                    break
                modes[headers.get("X-Fleet-Placement")] += 1
                who = headers.get("X-Fleet-Backend")
                if headers.get("X-Fleet-Placement") == "placed" \
                        and who not in amap[model]:
                    bad.append(f"{model} marked 'placed' but answered "
                               f"by {who} ∉ {amap[model]}")
        print(json.dumps({"phase": "steady-state",
                          "modes": dict(modes)}))
        if not modes.get("placed") \
                or modes.get("placed", 0) < sum(modes.values()) * 0.8:
            bad.append(f"steady-state traffic was not placement-"
                       f"routed: modes={dict(modes)}")

        # ---- phase 2: the footprint bound (the hint push must have
        # released non-placed copies by now; give one sweep of slack)
        time.sleep(1.0)
        resident, zoo_total = fleet_footprint()
        bound = (1 + replication) * zoo_total
        print(json.dumps({"phase": "footprint",
                          "fleet_resident_bytes": resident,
                          "zoo_total_bytes": zoo_total,
                          "bound_bytes": bound}))
        if zoo_total <= 0:
            bad.append("could not size the zoo from backend healthz")
        elif resident > bound:
            bad.append(f"fleet resident bytes {resident} exceed the "
                       f"(1+replication) x zoo bound {bound} — "
                       f"placement hints are not shrinking residency")

        # ---- phase 3: SIGKILL the hot tenant's owner mid-burst
        hot = "mnist"
        owner = amap[hot][0]
        owner_i = int(owner[1:])        # b0/b1/b2 -> port index
        answers: list[tuple] = []
        mu = threading.Lock()
        stop = threading.Event()

        def client(model: str):
            while not stop.is_set():
                try:
                    code, _b, headers = _post(
                        router_url, {"inputs": inputs[model]},
                        timeout=15, headers={"X-Model": model})
                except urllib.error.HTTPError as e:
                    code, headers = e.code, dict(e.headers)
                    e.read()
                except Exception:
                    code, headers = -1, {}
                with mu:
                    answers.append((code, "Retry-After" in headers))
                stop.wait(0.005)

        threads = [threading.Thread(target=client, args=(m,),
                                    daemon=True)
                   for m in (hot,) * 4 + ("wine", "kohonen")]
        for t in threads:
            t.start()
        time.sleep(1.0)
        procs[owner_i].kill()           # a CRASH, not a drain
        procs[owner_i].wait(timeout=15)
        healed = False
        for _ in range(80):
            placed = assignments().get(hot) or []
            if placed and owner not in placed:
                healed = True
                break
            time.sleep(0.25)
        time.sleep(1.0)                 # keep bursting post-heal
        stop.set()
        for t in threads:
            t.join(20.0)
        codes = collections.Counter(code for code, _ra in answers)
        print(json.dumps({"phase": "kill-burst", "owner": owner,
                          "healed": healed,
                          "codes": dict(sorted(codes.items()))}))
        if not healed:
            bad.append(f"placement never healed: {hot} still mapped "
                       f"to the killed backend {owner}")
        if codes.get(-1):
            bad.append(f"{codes[-1]} request(s) hung or died on a "
                       f"connection error during the kill burst")
        if codes.get(500):
            bad.append(f"{codes[500]} raw 500(s) during the kill "
                       f"burst")
        for code, ra in answers:
            if code in (429, 503) and not ra:
                bad.append(f"a {code} refusal carried no Retry-After")
                break

        # post-heal: the hot tenant answers from its NEW set, and the
        # footprint bound still holds on the surviving fleet
        amap = assignments()
        code, _b, headers = _post(router_url,
                                  {"inputs": inputs[hot]},
                                  timeout=30, headers={"X-Model": hot})
        if code != 200:
            bad.append(f"post-heal {hot} answered {code}")
        elif headers.get("X-Fleet-Placement") == "placed" \
                and headers.get("X-Fleet-Backend") \
                not in (amap.get(hot) or []):
            bad.append(f"post-heal {hot} 'placed' answer came from "
                       f"{headers.get('X-Fleet-Backend')} ∉ "
                       f"{amap.get(hot)}")
        time.sleep(1.0)
        resident, zoo_total = fleet_footprint()
        bound = (1 + replication) * zoo_total
        print(json.dumps({"phase": "footprint-post-heal",
                          "fleet_resident_bytes": resident,
                          "zoo_total_bytes": zoo_total,
                          "bound_bytes": bound}))
        if zoo_total > 0 and resident > bound:
            bad.append(f"post-heal fleet resident bytes {resident} "
                       f"exceed the bound {bound}")
        print(json.dumps({"scenario": "placement", "ok": not bad,
                          "violations": bad}))
        return 1 if bad else 0
    finally:
        if router_proc is not None:
            router_proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 15.0
        for proc in [router_proc] + list(procs.values()):
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1,
                                      deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _controlplane_scenario(args) -> int:
    """``--scenario controlplane`` — the crash-safe control plane
    acceptance (docs/fleet.md "Control-plane durability"): a REAL
    ``route --autoscale --state-dir`` process boots two managed serve
    children, takes admin mutations (a weight override + a placement
    pin), and is then SIGKILLed mid-burst.  Asserted:

    * the children survive the router crash (reparented, still
      serving) and a restarted router on the same port + state dir
      **re-adopts them in place**: same pids, journal shows ``adopt``
      records and exactly the original two ``boot`` records — zero
      orphans, zero double-boots, pinned by pid accounting;
    * while the restarted router reconciles, ``/predict`` answers
      503 + Retry-After (at least one observed) — never a hang, never
      a raw 500;
    * the journaled weight override and placement pin are live again
      after restart without any re-issued admin calls;
    * a static backend that answers ``/healthz`` green but serves
      latency-faulted predicts (the gray-failure mode) is demoted:
      its effective weight decays to ~zero (and its breaker trips)
      within a bounded number of probe intervals, while its own
      healthz stays 200;
    * zero raw 500s throughout; connection errors only inside the
      kill→restart gap; after SIGTERM the journal-and-keep default
      leaves the children running for the NEXT restart to re-adopt.
    """
    import collections
    import shutil
    import signal
    import socket
    import subprocess
    import sys
    import threading

    bad: list[str] = []
    x = [[0.1, 0.2, 0.3, 0.4]]
    tmp = tempfile.mkdtemp(prefix="znicz_chaos_cp_")
    state_dir = os.path.join(tmp, "state")
    child_pids: list[int] = []
    gray_proc = None
    router_proc = None

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def wait_healthz(url: str, proc, what: str,
                     tries: int = 240) -> bool:
        for _ in range(tries):
            try:
                with urllib.request.urlopen(url + "healthz",
                                            timeout=2) as r:
                    json.loads(r.read())
                return True
            except Exception:
                if proc is not None and proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    bad.append(f"{what} exited rc={proc.returncode}: "
                               f"{out[-300:]}")
                    return False
                time.sleep(0.25)
        bad.append(f"{what} never answered /healthz")
        return False

    def journal() -> list[dict]:
        path = os.path.join(state_dir, "controlplane.jsonl")
        out = []
        try:
            with open(path) as fh:
                for line in fh:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
        except FileNotFoundError:
            pass
        return out

    def alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    def boot_router(rport: int, extra: list[str]) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "route",
             "--port", str(rport), "--autoscale",
             "--min-backends", "2", "--max-backends", "3",
             "--placement", "1", "--state-dir", state_dir,
             "--probe-interval-s", "0.3",
             "--breaker-threshold", "2",
             "--breaker-cooldown-s", "1.0",
             "--reconcile-deadline-s", "20",
             "--serve-arg=--model", f"--serve-arg={model}",
             "--serve-arg=--max-wait-ms", "--serve-arg=1"] + extra,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    try:
        model = os.path.join(tmp, "demo.znn")
        _write_demo_znn(model)
        rport = free_port()
        router_url = f"http://127.0.0.1:{rport}/"

        # ---- phase 1: first boot — floor children + admin mutations
        router_proc = boot_router(rport, [])
        if not wait_healthz(router_url, router_proc, "router",
                            tries=480):
            return 1
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            boots = [e for e in journal() if e.get("kind") == "boot"]
            if len(boots) >= 2:
                break
            time.sleep(0.25)
        boots = [e for e in journal() if e.get("kind") == "boot"]
        child_pids = [int(e["pid"]) for e in boots]
        names = sorted(e["backend"] for e in boots)
        print(json.dumps({"phase": "boot", "children": names,
                          "pids": child_pids}))
        if len(boots) != 2 or not all(alive(p) for p in child_pids):
            bad.append(f"expected 2 live floor children, journal has "
                       f"{boots}")
            return 1
        req = urllib.request.Request(
            router_url + "admin/weight",
            json.dumps({"backend": names[0],
                        "weight": 2.5}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            if r.status != 200:
                bad.append(f"admin/weight answered {r.status}")
        req = urllib.request.Request(
            router_url + "admin/placement",
            json.dumps({"model": "demo",
                        "backends": [names[0]]}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            if r.status != 200:
                bad.append(f"admin/placement answered {r.status}")

        # ---- phase 2: burst clients + SIGKILL the control plane
        answers: list[tuple] = []
        mu = threading.Lock()
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    code, _b, headers = _post(router_url,
                                              {"inputs": x},
                                              timeout=15)
                except Exception:
                    code, headers = -1, {}
                with mu:
                    answers.append((time.monotonic(), code,
                                    "Retry-After" in headers))
                stop.wait(0.002)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        t_kill = time.monotonic()
        router_proc.kill()              # a CRASH, not a drain
        router_proc.wait(timeout=15)
        if not all(alive(p) for p in child_pids):
            bad.append("children died with the router — nothing to "
                       "re-adopt")
            return 1

        # a gray backend: healthz green, predicts latency-faulted
        gport = free_port()
        gray_url = f"http://127.0.0.1:{gport}/"
        gray_proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "serve",
             "--model", model, "--port", str(gport),
             "--max-wait-ms", "1", "--fault-plan",
             json.dumps({"faults": [
                 {"site": "engine.forward", "kind": "latency",
                  "latency_s": 0.4, "p": 1.0}]})],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        # ---- phase 3: restart on the same port + state dir
        router_proc = boot_router(rport, [
            "--backend", f"{gray_url},name=gray",
            "--gray-threshold-ms", "150",
            "--gray-strikes", "2", "--gray-decay", "0.3"])
        if not wait_healthz(router_url, router_proc, "router "
                            "(restarted)", tries=480):
            return 1
        t_up = time.monotonic()
        settled = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            rc = _health(router_url).get("reconcile") or {}
            if rc.get("state") == "settled":
                settled = True
                break
            time.sleep(0.2)
        t_settled = time.monotonic()
        if not settled:
            bad.append("restarted router never settled reconciliation")

        # re-adoption by pid accounting: same pids adopted, no new
        # boots for the managed names, every child accounted for
        entries = journal()
        adopts = [e for e in entries if e.get("kind") == "adopt"]
        boots2 = [e for e in entries if e.get("kind") == "boot"]
        adopted_pids = sorted(int(e["pid"]) for e in adopts)
        print(json.dumps({"phase": "reconcile", "settled": settled,
                          "adopted": sorted(e["backend"]
                                            for e in adopts),
                          "adopted_pids": adopted_pids,
                          "boot_records": len(boots2)}))
        if adopted_pids != sorted(child_pids):
            bad.append(f"re-adoption pids {adopted_pids} != surviving "
                       f"children {sorted(child_pids)}")
        if len(boots2) != 2:
            bad.append(f"{len(boots2)} boot records after restart — "
                       f"expected the original 2 (double-boot or "
                       f"leaked child)")
        if not all(alive(p) for p in child_pids):
            bad.append("a re-adopted child died during reconciliation")

        # journaled decisions are live again, with no re-issued admin
        health = _health(router_url)
        rows = {r["name"]: r for r in health.get("backends") or []}
        if names[0] not in rows:
            bad.append(f"{names[0]} missing after re-adoption")
        elif abs(rows[names[0]]["weight"] - 2.5) > 1e-6:
            bad.append(f"journaled weight lost: {names[0]} weighs "
                       f"{rows[names[0]]['weight']}, expected 2.5")
        pins = (health.get("placement") or {}).get("pins") or {}
        if pins.get("demo") != [names[0]]:
            bad.append(f"journaled pin lost: pins={pins}")

        # ---- phase 4: gray demotion — probe-green, predict-sick
        demoted = False
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            rows = {r["name"]: r
                    for r in _health(router_url).get("backends") or []}
            g = rows.get("gray")
            if g is not None and g["effective_weight"] <= 0.05:
                demoted = True
                break
            time.sleep(0.3)
        gray_rows = rows.get("gray") or {}
        try:
            with urllib.request.urlopen(gray_url + "healthz",
                                        timeout=5) as r:
                gray_healthz = r.status
        except Exception:
            gray_healthz = -1
        print(json.dumps({"phase": "gray", "demoted": demoted,
                          "effective_weight":
                              gray_rows.get("effective_weight"),
                          "breaker":
                              (gray_rows.get("breaker")
                               or {}).get("state"),
                          "gray_healthz": gray_healthz}))
        if not demoted:
            bad.append(f"gray backend never demoted: {gray_rows}")
        if gray_healthz != 200:
            bad.append(f"gray backend healthz answered {gray_healthz}"
                       f" — the drill needs probe-green")

        stop.set()
        for t in threads:
            t.join(20.0)

        # ---- the ledger of every answer across the whole arc
        codes = collections.Counter(c for _t, c, _ra in answers)
        in_gap = [c for t, c, _ra in answers if t_kill <= t <= t_up]
        stray = sum(1 for t, c, _ra in answers
                    if c == -1 and not t_kill <= t <= t_up)
        reconcile_503 = sum(
            1 for t, c, ra in answers
            if c == 503 and ra and t_kill <= t <= t_settled)
        naked = sum(1 for _t, c, ra in answers
                    if c in (429, 503) and not ra)
        print(json.dumps({"phase": "ledger",
                          "codes": dict(sorted(codes.items())),
                          "gap_answers": len(in_gap),
                          "reconcile_503s": reconcile_503}))
        if codes.get(500):
            bad.append(f"{codes[500]} raw 500(s) during the arc")
        if stray:
            bad.append(f"{stray} connection error(s) OUTSIDE the "
                       f"kill→restart gap")
        if not reconcile_503:
            bad.append("no 503+Retry-After observed during restart "
                       "reconciliation")
        if naked:
            bad.append(f"{naked} refusal(s) carried no Retry-After")
        if not codes.get(200):
            bad.append("no successful answers at all — the burst "
                       "never exercised the fleet")

        # ---- phase 5: journal-and-keep — SIGTERM leaves children up
        router_proc.send_signal(signal.SIGTERM)
        try:
            router_proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            router_proc.kill()
            bad.append("router did not exit on SIGTERM")
        survivors = [p for p in child_pids if alive(p)]
        print(json.dumps({"phase": "journal-and-keep",
                          "surviving_children": survivors}))
        if sorted(survivors) != sorted(child_pids):
            bad.append(f"journal-and-keep default still drained "
                       f"children: survivors={survivors}")
        print(json.dumps({"scenario": "controlplane", "ok": not bad,
                          "violations": bad}))
        return 1 if bad else 0
    finally:
        for proc in (router_proc, gray_proc):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for pid in child_pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + 15.0
        for proc in (router_proc, gray_proc):
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1,
                                      deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        for pid in child_pids:
            for _ in range(100):
                if not alive(pid):
                    break
                time.sleep(0.1)
            else:
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
        shutil.rmtree(tmp, ignore_errors=True)


def _ha_scenario(args) -> int:
    """``--scenario ha`` — the highly-available fleet front
    acceptance (docs/fleet.md "Router high availability"): a REAL
    primary ``route --autoscale --state-dir`` boots three managed
    serve children while a REAL hot standby (``--standby-of``) tails
    the same journal, probes the primary, and refuses traffic with
    503 + Retry-After.  The primary is SIGKILLed mid-burst.
    Asserted:

    * the standby acquires the lease (the dead holder's pid identity
      makes the lease acquirable before TTL expiry), bumps the epoch
      exactly once, adopts the journal's live children and serves:
      failing-over clients see a 200 within 2x the lease TTL of the
      kill — zero raw 500s across the whole arc, refusals always
      carry Retry-After;
    * the journaled admin weight override is live on the promoted
      standby without any re-issued admin call (the journal tailer
      kept the control plane warm);
    * the resurrected old primary rejoins as a FENCED standby: it
      sees the newer epoch, refuses admin mutations with
      503 + Retry-After, and never double-boots a child;
    * journal accounting: ``lease`` epochs exactly ``[1, 2]``,
      exactly the original three ``boot`` records, zero ``drain``
      records, and zero epoch-1 mutations after the epoch-2 bump.
    """
    import collections
    import shutil
    import signal
    import socket
    import subprocess
    import sys
    import threading

    bad: list[str] = []
    x = [[0.1, 0.2, 0.3, 0.4]]
    ttl = 2.0
    tmp = tempfile.mkdtemp(prefix="znicz_chaos_ha_")
    state_dir = os.path.join(tmp, "state")
    child_pids: list[int] = []
    procs: list = []                  # every route proc ever booted

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def wait_healthz(url: str, proc, what: str,
                     tries: int = 240) -> bool:
        for _ in range(tries):
            try:
                with urllib.request.urlopen(url + "healthz",
                                            timeout=2) as r:
                    json.loads(r.read())
                return True
            except Exception:
                if proc is not None and proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    bad.append(f"{what} exited rc={proc.returncode}: "
                               f"{out[-300:]}")
                    return False
                time.sleep(0.25)
        bad.append(f"{what} never answered /healthz")
        return False

    def journal() -> list[dict]:
        path = os.path.join(state_dir, "controlplane.jsonl")
        out = []
        try:
            with open(path) as fh:
                for line in fh:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
        except FileNotFoundError:
            pass
        return out

    def alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    def role_of(url: str) -> str:
        try:
            return str((_health(url).get("ha") or {})
                       .get("role") or "?")
        except Exception:
            return "?"

    def boot_router(rport: int, extra: list[str]) -> subprocess.Popen:
        proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "route",
             "--port", str(rport), "--autoscale",
             "--min-backends", "3", "--max-backends", "4",
             "--state-dir", state_dir,
             "--lease-ttl-s", str(ttl),
             "--probe-interval-s", "0.3",
             "--reconcile-deadline-s", "20",
             "--serve-arg=--model", f"--serve-arg={model}",
             "--serve-arg=--max-wait-ms", "--serve-arg=1"] + extra,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        procs.append(proc)
        return proc

    try:
        model = os.path.join(tmp, "demo.znn")
        _write_demo_znn(model)
        aport, bport = free_port(), free_port()
        a_url = f"http://127.0.0.1:{aport}/"
        b_url = f"http://127.0.0.1:{bport}/"

        # ---- phase 1: primary boots the floor fleet + one mutation
        proc_a = boot_router(aport, [])
        if not wait_healthz(a_url, proc_a, "primary", tries=480):
            return 1
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            boots = [e for e in journal() if e.get("kind") == "boot"]
            if len(boots) >= 3:
                break
            time.sleep(0.25)
        boots = [e for e in journal() if e.get("kind") == "boot"]
        child_pids = [int(e["pid"]) for e in boots]
        names = sorted(e["backend"] for e in boots)
        print(json.dumps({"phase": "boot", "children": names,
                          "pids": child_pids,
                          "role": role_of(a_url)}))
        if len(boots) != 3 or not all(alive(p) for p in child_pids):
            bad.append(f"expected 3 live floor children, journal has "
                       f"{boots}")
            return 1
        if role_of(a_url) != "primary":
            bad.append("first router did not take the lease as "
                       "primary")
        req = urllib.request.Request(
            a_url + "admin/weight",
            json.dumps({"backend": names[0],
                        "weight": 2.5}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            if r.status != 200:
                bad.append(f"admin/weight answered {r.status}")

        # ---- phase 2: hot standby tails the journal, refuses traffic
        proc_b = boot_router(bport, ["--standby-of", a_url])
        if not wait_healthz(b_url, proc_b, "standby", tries=480):
            return 1
        deadline = time.monotonic() + 20.0
        while role_of(b_url) != "standby" \
                and time.monotonic() < deadline:
            time.sleep(0.2)
        code, _body, hdrs = _post(b_url, {"inputs": x}, timeout=10)
        print(json.dumps({"phase": "standby", "role": role_of(b_url),
                          "refusal_code": code,
                          "retry_after": hdrs.get("Retry-After")}))
        if role_of(b_url) != "standby":
            bad.append("second router never settled as standby")
        if code != 503 or "Retry-After" not in hdrs:
            bad.append(f"standby /predict answered {code} "
                       f"(headers {sorted(hdrs)}) — wanted a "
                       f"503 + Retry-After refusal")

        # ---- phase 3: burst clients (failover list) + SIGKILL
        urls = [a_url, b_url]
        answers: list[tuple] = []
        mu = threading.Lock()
        stop = threading.Event()

        def client():
            active = 0
            while not stop.is_set():
                u = urls[active % len(urls)]
                try:
                    code, _b, headers = _post(u, {"inputs": x},
                                              timeout=15)
                except Exception:
                    # transport error: rotate to the next router —
                    # an HTTP answer (even a refusal) never rotates
                    code, headers = -1, {}
                    active += 1
                with mu:
                    answers.append((time.monotonic(), code,
                                    "Retry-After" in headers))
                stop.wait(0.002)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        t_kill = time.monotonic()
        proc_a.kill()                 # a CRASH, not a handoff
        proc_a.wait(timeout=15)
        if not all(alive(p) for p in child_pids):
            bad.append("children died with the primary — nothing for "
                       "the standby to adopt")
            return 1

        # ---- phase 4: the standby takes over and serves
        deadline = time.monotonic() + 30.0
        while role_of(b_url) != "primary" \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        t_takeover = time.monotonic()
        if role_of(b_url) != "primary":
            bad.append("standby never took the lease after the kill")
        first_ok = None
        deadline = time.monotonic() + 30.0
        while first_ok is None and time.monotonic() < deadline:
            with mu:
                oks = [t for t, c, _ra in answers
                       if c == 200 and t > t_kill]
            if oks:
                first_ok = min(oks)
                break
            time.sleep(0.1)
        gap_s = None if first_ok is None else first_ok - t_kill
        print(json.dumps({"phase": "takeover",
                          "role": role_of(b_url),
                          "first_200_after_kill_s":
                              None if gap_s is None
                              else round(gap_s, 3)}))
        if first_ok is None:
            bad.append("no 200 at all after the kill — the standby "
                       "never served")
        elif gap_s > 2 * ttl:
            bad.append(f"first 200 came {gap_s:.2f}s after the kill "
                       f"— the 2x lease TTL bound is {2 * ttl:.1f}s")
        # the journaled weight must come back live on the promoted
        # standby — adoption + weight replay settle asynchronously
        # after the lease flips (and the first 200 can be the dying
        # primary's), so poll to the reconcile deadline
        deadline = time.monotonic() + 20.0
        weight = None
        while time.monotonic() < deadline:
            h = _health(b_url)
            rows = {r["name"]: r for r in h.get("backends") or []}
            weight = (rows.get(names[0]) or {}).get("weight")
            if (h.get("reconcile") or {}).get("state") == "settled" \
                    and weight is not None \
                    and abs(weight - 2.5) <= 1e-6:
                break
            time.sleep(0.2)
        if weight is None:
            bad.append(f"{names[0]} missing on the promoted standby")
        elif abs(weight - 2.5) > 1e-6:
            bad.append(f"journaled weight lost across failover: "
                       f"{names[0]} weighs {weight}, expected 2.5")

        # ---- phase 5: the old primary resurrects as a fenced standby
        proc_a2 = boot_router(aport, [])
        if not wait_healthz(a_url, proc_a2, "resurrected primary",
                            tries=480):
            return 1
        deadline = time.monotonic() + 20.0
        while role_of(a_url) != "standby" \
                and time.monotonic() < deadline:
            time.sleep(0.2)
        code, body, hdrs = 0, {}, {}
        req = urllib.request.Request(
            a_url + "admin/weight",
            json.dumps({"backend": names[0],
                        "weight": 9.0}).encode(),
            {"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                code, hdrs = r.status, dict(r.headers)
        except urllib.error.HTTPError as e:
            code, hdrs = e.code, dict(e.headers)
        print(json.dumps({"phase": "fenced-rejoin",
                          "role": role_of(a_url),
                          "stale_admin_code": code}))
        if role_of(a_url) != "standby":
            bad.append(f"resurrected old primary came back as "
                       f"{role_of(a_url)!r} — wanted a fenced "
                       f"standby")
        if code != 503 or "Retry-After" not in hdrs:
            bad.append(f"stale admin mutation answered {code} — "
                       f"wanted a fenced 503 + Retry-After")
        rows = {r["name"]: r
                for r in _health(b_url).get("backends") or []}
        if names[0] in rows \
                and abs(rows[names[0]]["weight"] - 2.5) > 1e-6:
            bad.append("a STALE admin mutation reached the fleet "
                       "through the deposed primary")

        stop.set()
        for t in threads:
            t.join(20.0)

        # ---- the ledger + the journal's leadership history
        codes = collections.Counter(c for _t, c, _ra in answers)
        naked = sum(1 for _t, c, ra in answers
                    if c in (429, 503) and not ra)
        stray = sum(1 for t, c, _ra in answers
                    if c == -1
                    and not t_kill - 0.1 <= t <= t_takeover + 1.0)
        entries = journal()
        leases = [e for e in entries if e.get("kind") == "lease"]
        epochs = [int(e.get("epoch", 0)) for e in leases]
        boots2 = [e for e in entries if e.get("kind") == "boot"]
        drains = [e for e in entries if e.get("kind") == "drain"]
        stale_mut = []
        if epochs == [1, 2]:
            bump_at = entries.index(leases[1])
            stale_mut = [e for e in entries[bump_at + 1:]
                         if int(e.get("epoch", 2)) < 2]
        print(json.dumps({"phase": "ledger",
                          "codes": dict(sorted(codes.items())),
                          "lease_epochs": epochs,
                          "boot_records": len(boots2),
                          "drain_records": len(drains),
                          "stale_epoch_records": len(stale_mut)}))
        if codes.get(500):
            bad.append(f"{codes[500]} raw 500(s) during the arc")
        if naked:
            bad.append(f"{naked} refusal(s) carried no Retry-After")
        if stray:
            bad.append(f"{stray} connection error(s) outside the "
                       f"kill→takeover window")
        if not codes.get(200):
            bad.append("no successful answers at all — the burst "
                       "never exercised the fleet")
        if epochs != [1, 2]:
            bad.append(f"lease epochs {epochs} — wanted exactly one "
                       f"takeover bump [1, 2]")
        if len(boots2) != 3:
            bad.append(f"{len(boots2)} boot records — expected the "
                       f"original 3 (a double-boot leaked a child)")
        if drains:
            bad.append(f"{len(drains)} drain record(s) — nothing "
                       f"should have been drained")
        if stale_mut:
            bad.append(f"{len(stale_mut)} stale epoch-1 record(s) "
                       f"accepted after the epoch-2 bump")
        print(json.dumps({"scenario": "ha", "ok": not bad,
                          "violations": bad}))
        return 1 if bad else 0
    finally:
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for pid in child_pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + 15.0
        for proc in procs:
            if proc is None or proc.poll() is not None:
                continue
            try:
                proc.wait(timeout=max(0.1,
                                      deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        for pid in child_pids:
            for _ in range(100):
                if not alive(pid):
                    break
                time.sleep(0.1)
            else:
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
        shutil.rmtree(tmp, ignore_errors=True)


def _trace_scenario(args) -> int:
    """``--scenario trace`` — the distributed-tracing acceptance
    (docs/observability.md "Distributed tracing"): two REAL ``serve``
    backends behind a REAL ``route`` process, one backend slowed by an
    injected ``engine.forward`` latency fault.  A mixed burst (JSON +
    error + deadline-expired traffic) must leave the router's
    ``/tracez`` holding assembled cross-hop traces: the slow ones
    (``?min_ms=``) dominated by the injected stage, EVERY
    error/deadline trace retained, and each full trace's stage sum
    within tolerance of its measured e2e wall.  Then ``bench.py serve
    --trace-breakdown`` (when the repo checkout is present) must print
    a per-stage decomposition whose p50 stage sum lands within 10% of
    the e2e p50."""
    import shutil
    import signal
    import socket
    import subprocess
    import sys
    import threading

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    slow_s = max(0.05, float(args.slow_s))
    tmp = tempfile.mkdtemp(prefix="znicz_chaos_trace_")
    procs: list = []
    router_proc = None

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def wait_healthz(url: str, proc, what: str,
                     tries: int = 240) -> bool:
        for _ in range(tries):
            try:
                with urllib.request.urlopen(url + "healthz",
                                            timeout=2) as r:
                    json.loads(r.read())
                return True
            except Exception:
                if proc is not None and proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    bad.append(f"{what} exited rc={proc.returncode}: "
                               f"{out[-300:]}")
                    return False
                time.sleep(0.25)
        bad.append(f"{what} never answered /healthz")
        return False

    try:
        model = os.path.join(tmp, "demo.znn")
        _write_demo_znn(model)
        ports = [free_port(), free_port()]
        rport = free_port()
        router_url = f"http://127.0.0.1:{rport}/"
        slow_plan = json.dumps({"faults": [
            {"site": "engine.forward", "kind": "latency",
             "latency_s": slow_s, "p": 1.0}]})
        for i, port in enumerate(ports):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "znicz_tpu", "serve",
                 "--model", model, "--port", str(port),
                 "--max-wait-ms", "1", "--warmup-shape", "4"]
                + (["--fault-plan", slow_plan] if i == 1 else []),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        for i, port in enumerate(ports):
            if not wait_healthz(f"http://127.0.0.1:{port}/",
                                procs[i], f"backend {i}"):
                return 1
        # head-rate 1.0: the drill asserts RETENTION CONTENT, so every
        # assembled trace must land in the store (the sampling-policy
        # math itself is pinned by tests/test_tracing.py)
        router_proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "route",
             "--port", str(rport), "--probe-interval-s", "0.3",
             "--trace-sample", "1.0", "--trace-head-rate", "1.0"]
            + [f for i, port in enumerate(ports)
               for f in ("--backend",
                         f"http://127.0.0.1:{port}/,name=b{i}")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        if not wait_healthz(router_url, router_proc, "router"):
            return 1

        # ---- the mixed burst: plain traffic spread over both
        # backends, plus deliberate error and dead-on-arrival traffic
        n_ok = 40
        n_err = 5
        n_dead = 3
        codes: list = []
        walls: dict = {}        # trace_id -> client-measured e2e ms

        def one(hdrs: dict | None = None,
                body: dict | None = None) -> tuple:
            t0 = time.monotonic()
            code, _b, headers = _post(router_url,
                                      body or {"inputs": x},
                                      timeout=60, headers=hdrs)
            return code, headers, (time.monotonic() - t0) * 1e3

        mu = threading.Lock()

        def burst(n: int):
            for _ in range(n):
                try:
                    code, _h, _w = one()
                except Exception:
                    code = -1
                with mu:
                    codes.append(code)

        threads = [threading.Thread(target=burst, args=(n_ok // 4,),
                                    daemon=True) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        for _ in range(n_err):     # unknown tenant -> backend 404
            code, _h, _w = one(hdrs={"X-Model": "no-such-tenant"})
            codes.append(code)
        for _ in range(n_dead):    # dead on arrival -> router 504
            code, _h, _w = one(hdrs={"X-Deadline-Ms": "0.000001"})
            codes.append(code)
        if codes.count(-1):
            bad.append(f"{codes.count(-1)} request(s) hung during "
                       f"the burst")

        def tracez(qs: str = "") -> dict:
            with urllib.request.urlopen(router_url + "tracez" + qs,
                                        timeout=10) as r:
                return json.loads(r.read())

        # ---- assertion 1: the slow traces exist, are fully
        # assembled, and the injected hop dominates them
        min_ms = slow_s * 1e3 * 0.6
        slow = tracez(f"?min_ms={min_ms:.0f}&outcome=ok")
        slow_traces = [t for t in slow.get("traces", ())
                       if t.get("backend") == "b1"]
        print(json.dumps({"phase": "slow-tail",
                          "retained_over_min_ms": slow.get("retained"),
                          "b1_traces": len(slow_traces)}))
        if not slow_traces:
            bad.append(f"/tracez?min_ms={min_ms:.0f} holds no trace "
                       f"from the slowed backend b1")
        for t in slow_traces:
            stages = t.get("stages") or {}
            present = {k: v for k, v in stages.items()
                       if v is not None}
            if set(present) != set(slow.get("stages", ())):
                bad.append(f"slow trace {t.get('trace_id')} is not "
                           f"fully assembled: {sorted(present)}")
                break
            dominant = max(present, key=present.get)
            if dominant != "engine.forward":
                bad.append(f"slow trace {t.get('trace_id')} is "
                           f"dominated by {dominant} "
                           f"({present[dominant]:.1f}ms), expected "
                           f"the injected engine.forward")
                break
            total = t.get("total_ms") or 0.0
            sum_ms = sum(present.values())
            if total > 0 and abs(sum_ms - total) / total > 0.10:
                bad.append(f"slow trace {t.get('trace_id')}: stage "
                           f"sum {sum_ms:.1f}ms vs e2e "
                           f"{total:.1f}ms — off by more than 10%")
                break

        # ---- assertion 2: every error/deadline trace retained
        errs = tracez("?outcome=error")
        deads = tracez("?outcome=deadline")
        print(json.dumps({"phase": "error-retention",
                          "errors": errs.get("retained"),
                          "deadlines": deads.get("retained")}))
        if (errs.get("retained") or 0) < n_err:
            bad.append(f"only {errs.get('retained')} error traces "
                       f"retained, {n_err} were driven")
        if (deads.get("retained") or 0) < n_dead:
            bad.append(f"only {deads.get('retained')} deadline traces "
                       f"retained, {n_dead} were driven")
        for t in deads.get("traces", ()):
            if (t.get("stages") or {}).get("net.hop") is not None:
                bad.append("a dead-on-arrival trace claims a net.hop "
                           "stage — it never reached a backend")
                break

        # ---- assertion 3: bench's client-side decomposition agrees
        # with its own e2e measurement (the repo checkout's bench.py;
        # absent in an installed-package run — skipped, not failed)
        bench = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "bench.py")
        if os.path.exists(bench):
            out = subprocess.run(
                [sys.executable, bench, "serve",
                 "--serve-duration-s", "2", "--serve-clients", "2",
                 "--trace-breakdown"],
                capture_output=True, text=True, timeout=300)
            row = {}
            for line in out.stdout.strip().splitlines():
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
            br = row.get("trace_breakdown") or {}
            print(json.dumps({"phase": "bench-breakdown",
                              "traces": br.get("traces"),
                              "sum_over_e2e": br.get("sum_over_e2e")}))
            if not br.get("traces"):
                bad.append(f"bench --trace-breakdown assembled no "
                           f"traces: {row.get('error')!r}")
            elif not 0.9 <= (br.get("sum_over_e2e") or 0.0) <= 1.1:
                bad.append(f"bench stage sum is off its own e2e by "
                           f"more than 10%: "
                           f"sum_over_e2e={br.get('sum_over_e2e')}")
            missing = [s for s in (slow.get("stages") or ())
                       if s not in (br.get("stages") or {})]
            if br.get("traces") and missing:
                bad.append(f"bench breakdown is missing stages: "
                           f"{missing}")
        else:
            print(json.dumps({"phase": "bench-breakdown",
                              "skipped": "no repo bench.py"}))

        print(json.dumps({"scenario": "trace", "ok": not bad,
                          "violations": bad}))
        return 1 if bad else 0
    finally:
        for proc in [router_proc] + procs:
            if proc is not None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 15.0
        for proc in [router_proc] + procs:
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1,
                                      deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _admin_reload_named(url: str, name: str, model: str,
                        timeout: float = 60.0):
    """(status, body) of a synchronous per-model ``POST
    /admin/reload`` naming a zoo entry."""
    req = urllib.request.Request(
        url + "admin/reload",
        json.dumps({"name": name, "model": model,
                    "wait": True}).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="znicz_tpu chaos",
        description="smoke the serving stack under an injected "
                    "engine.forward fault (see docs/resilience.md)")
    p.add_argument("--model", default=None,
                   help=".znn to serve (default: a tiny built-in demo "
                        "model)")
    p.add_argument("--plan", default=None,
                   help="fault plan: inline JSON or @file (default: a "
                        "canned engine.forward fault that exhausts "
                        "after tripping the breaker)")
    p.add_argument("--requests", type=int, default=8,
                   help="requests to fire while the fault is live")
    p.add_argument("--breaker-threshold", type=int, default=2)
    p.add_argument("--cooldown-s", type=float, default=1.0)
    p.add_argument("--retry-attempts", type=int, default=2)
    p.add_argument("--scenario", default="breaker",
                   choices=("breaker", "reload", "promote", "overload",
                            "zoo", "slo", "wire", "fleet", "online",
                            "placement", "controlplane", "trace",
                            "san", "ha"),
                   help="breaker: the engine-fault degradation arc "
                        "(default); reload: hot-reload a corrupted "
                        "artifact and assert rollback + zero downtime "
                        "(docs/durability.md); promote: the closed "
                        "loop — N promotions under fault injection "
                        "plus a regressed candidate auto-rolled-back "
                        "by the SLO watch (docs/promotion.md); "
                        "overload: sustained past-capacity load with "
                        "one latency-faulted replica — deadlines, "
                        "retry budget, hedging, adaptive shedding and "
                        "graceful drain all asserted "
                        "(docs/resilience.md); zoo: three model "
                        "families in one multi-tenant server under a "
                        "memory budget that forces weight eviction, "
                        "one tenant latency-faulted, one hot-reloaded "
                        "mid-burst — routing, residency byte-"
                        "identity, criticality classes and reload "
                        "isolation asserted (docs/serving.md); slo: "
                        "two tenants with latency SLOs judged by a "
                        "live burn-rate engine on sub-second windows, "
                        "one tenant latency-faulted — exactly one "
                        "alert for the burning tenant, the quiet "
                        "tenant's budget intact, zero raw 500s, and "
                        "the per-tenant device-ms ledger adds up "
                        "(docs/observability.md); wire: JSON + "
                        "binary + malformed-binary traffic against "
                        "an int8-quantized memoizing server under a "
                        "transient device fault — zero raw 500s on "
                        "either format, junk binary answers 400 "
                        "fast, cross-format parity, and a reload "
                        "swaps the memo key space (docs/serving.md "
                        "'Wire protocol'); fleet: three REAL serve "
                        "processes behind a REAL route process — one "
                        "SIGKILLed mid-burst then restarted (zero "
                        "raw 500s/hangs, ejection + re-admission), "
                        "one rolling promotion walked to completion "
                        "and a regressed candidate rolled back "
                        "fleet-wide mid-walk (docs/fleet.md); "
                        "online: the live-data loop — capture tap on "
                        "a real server, continual trainer replaying "
                        "it in bless/refuse rounds, promotion watcher "
                        "deploying blessed candidates; a poisoned "
                        "round refused at blessing, a blessed-but-"
                        "toxic candidate rolled back by the SLO "
                        "watch, capture fail-open fault-injected, "
                        "plus the Kohonen serve-and-train drill "
                        "(docs/online.md); placement: three "
                        "multi-tenant serve processes behind a route "
                        "--placement process — the map covers every "
                        "tenant, traffic routes inside placement "
                        "sets, fleet resident bytes stay ≤ "
                        "(1+replication) x one zoo, and SIGKILLing "
                        "the hot tenant's owner mid-burst heals via "
                        "re-placement with zero raw 500s "
                        "(docs/fleet.md); controlplane: a route "
                        "--autoscale --state-dir process SIGKILLed "
                        "mid-burst and restarted — journaled weights/"
                        "pins restored, surviving children re-adopted "
                        "in place (zero orphans/double-boots, pinned "
                        "by pid accounting), 503+Retry-After while "
                        "reconciling, and a healthz-green/predict-"
                        "sick backend gray-demoted to ~zero effective "
                        "weight (docs/fleet.md 'Control-plane "
                        "durability'); trace: two serve backends "
                        "behind a route process, one slowed by an "
                        "injected engine.forward latency — /tracez"
                        "?min_ms= must hold fully-assembled cross-hop "
                        "traces dominated by the injected stage, "
                        "every error/deadline trace retained, stage "
                        "sums within 10%% of e2e, and bench.py serve "
                        "--trace-breakdown agreeing with its own e2e "
                        "(docs/observability.md 'Distributed "
                        "tracing'); ha: a primary route --state-dir "
                        "and a hot standby over the same journal — "
                        "the primary SIGKILLed mid-burst, the "
                        "standby takes the lease (one epoch bump), "
                        "adopts the children and serves within 2x "
                        "the lease TTL, the resurrected old primary "
                        "rejoins as a FENCED standby refusing stale "
                        "mutations, zero raw 500s (docs/fleet.md "
                        "'Router high availability')")
    p.add_argument("--promotions", type=int, default=3,
                   help="promote: good candidates to drive through "
                        "the loop before the regressed one")
    p.add_argument("--watch-s", type=float, default=1.2,
                   help="promote: SLO watch window per promotion")
    p.add_argument("--max-p99-ms", type=float, default=50.0,
                   help="promote: p99 latency objective the regressed "
                        "candidate must breach")
    p.add_argument("--bad-latency-s", type=float, default=0.08,
                   help="promote: per-forward latency injected while "
                        "the regressed candidate serves")
    p.add_argument("--duration-s", type=float, default=3.5,
                   help="overload: seconds of sustained load per "
                        "phase (unhedged, then hedged; the first "
                        "second is warm-up, excluded from p99)")
    p.add_argument("--clients", type=int, default=8,
                   help="overload: concurrent client threads (offered "
                        "load is several times the faulted fleet's "
                        "capacity)")
    p.add_argument("--slow-s", type=float, default=0.25,
                   help="overload: latency injected at replica.slow.0 "
                        "— the one slow-but-not-sick replica")
    p.add_argument("--hedge-after-ms", type=float, default=30.0,
                   help="overload: fixed hedge trigger for the hedged "
                        "phase (fixed, not p95, so the drill is "
                        "deterministic)")
    p.add_argument("--budget-ratio", type=float, default=0.1,
                   help="overload: retry-budget refill fraction under "
                        "test")
    p.add_argument("--zoo-budget-frac", type=float, default=0.6,
                   help="zoo: weight-residency budget as a fraction "
                        "of the demo zoo's combined weight bytes "
                        "(< 1 forces eviction while all tenants "
                        "cycle)")
    p.add_argument("--slo-threshold-ms", type=float, default=50.0,
                   help="slo: the latency objective's good/bad "
                        "threshold — the injected fault (--slow-s) "
                        "must land well past it, quiet-tenant "
                        "forwards well under it")
    p.add_argument("--slo-fast-s", type=float, default=1.0,
                   help="slo: fast burn window (the slow window is "
                        "3x, the snapshot tick a fifth)")
    p.add_argument("--slo-burn", type=float, default=2.0,
                   help="slo: burn-rate alert threshold both windows "
                        "must exceed to fire")
    args = p.parse_args(argv)
    if args.scenario == "reload":
        return _reload_scenario(args)
    if args.scenario == "promote":
        return _promote_scenario(args)
    if args.scenario == "overload":
        return _overload_scenario(args)
    if args.scenario == "zoo":
        return _zoo_scenario(args)
    if args.scenario == "slo":
        return _slo_scenario(args)
    if args.scenario == "wire":
        return _wire_scenario(args)
    if args.scenario == "fleet":
        return _fleet_scenario(args)
    if args.scenario == "online":
        return _online_scenario(args)
    if args.scenario == "placement":
        return _placement_scenario(args)
    if args.scenario == "controlplane":
        return _controlplane_scenario(args)
    if args.scenario == "trace":
        return _trace_scenario(args)
    if args.scenario == "san":
        return _san_scenario(args)
    if args.scenario == "ha":
        return _ha_scenario(args)

    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    tmp = None
    model = args.model
    if model is None:
        tmp = tempfile.TemporaryDirectory(prefix="znicz_chaos_")
        model = os.path.join(tmp.name, "demo.znn")
        _write_demo_znn(model)

    if args.plan is not None:
        plan = faults.parse_plan(args.plan)
    else:
        # fail exactly long enough to trip the breaker through the
        # retries, then recover — the full closed→open→half_open→
        # closed arc (each pre-trip request burns retry_attempts
        # firings; the half-open probe must find the fault gone)
        times = args.retry_attempts * args.breaker_threshold
        plan = faults.FaultPlan([faults.FaultSpec(
            "engine.forward", times=times,
            message="chaos: injected transient device fault")], seed=7)
    faults.install(plan)

    engine = ServingEngine(
        model, backend="jax", buckets=(1, 2),
        retry=RetryPolicy(max_attempts=args.retry_attempts,
                          base_delay_s=0.01, max_delay_s=0.05),
        breaker=CircuitBreaker(failure_threshold=args.breaker_threshold,
                               cooldown_s=args.cooldown_s))
    server = ServingServer(engine, max_wait_ms=1.0).start()
    x = [[0.1, -0.2, 0.3, 0.4]]
    codes, bad = [], []
    try:
        for i in range(args.requests):
            status, body, headers = _post(server.url, {"inputs": x})
            health = _health(server.url)["status"]
            codes.append(status)
            if status not in (200, 503):
                bad.append(f"request {i}: unexpected status {status} "
                           f"({body.get('error')})")
            if status == 503 and "Retry-After" not in headers:
                bad.append(f"request {i}: 503 without Retry-After")
            print(json.dumps({"request": i, "status": status,
                              "health": health,
                              "breaker": engine.breaker.state}))
        # fault plan exhausted by now: wait out the cooldown, then one
        # request must probe half-open and close the circuit
        time.sleep(args.cooldown_s + 0.1)
        status, body, _ = _post(server.url, {"inputs": x})
        health = _health(server.url)
        print(json.dumps({"request": "post-recovery", "status": status,
                          "health": health["status"],
                          "breaker": engine.breaker.state}))
        if status != 200:
            bad.append(f"post-recovery request got {status}, "
                       f"expected 200")
        if engine.breaker.state != "closed":
            bad.append(f"breaker did not close after recovery "
                       f"(state={engine.breaker.state})")
        if health["status"] != "ok":
            bad.append(f"healthz stuck at {health['status']!r} "
                       f"after recovery")
        m = engine.breaker.metrics()
        summary = {"codes": codes, "fired": plan.snapshot(),
                   "breaker": m, "engine": {
                       k: v for k, v in engine.metrics().items()
                       if k in ("forward_calls", "forward_failures",
                                "fallback_calls", "retries")},
                   "ok": not bad, "violations": bad}
        print(json.dumps(summary))
    finally:
        faults.uninstall(plan)
        server.stop()
        engine.close()
        if tmp is not None:
            tmp.cleanup()
    if bad:
        return 1
    if m["trips"] < 1:
        print(json.dumps({"ok": False, "violations":
                          ["fault never tripped the breaker — plan "
                           "too weak for the configured threshold"]}))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
