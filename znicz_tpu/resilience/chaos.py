"""``python -m znicz_tpu chaos`` — serving-under-fault smoke mode.

Boots the real HTTP serving stack (engine + micro-batcher + server)
under a canned :class:`~.faults.FaultPlan`, drives traffic through the
whole breaker lifecycle, and verifies the graceful-degradation
contract end to end:

* with a persistent ``engine.forward`` fault every request still
  resolves — native-fallback 200 or 503 + Retry-After, never a raw 500
  and never a hang;
* ``/healthz`` leaves ``ok`` while the circuit is open (``degraded`` /
  ``open``);
* once the fault clears, a half-open probe closes the breaker and
  ``/healthz`` returns to ``ok``.

A second drill, ``--scenario reload``, smokes the durability layer
(docs/durability.md): a hot reload of a deterministically bit-rotted
artifact must roll back — verify fails, the generation stays put, the
old model keeps answering 200s with identical bytes — and a subsequent
good artifact must swap with zero downtime.

Exit code 0 when every invariant holds — tools/chaos_smoke.sh wires
this into CI-ish usage.  The same ``FaultPlan`` mechanism drives the
pytest ``chaos`` marker; this mode exists so an operator can smoke a
REAL server (their model, their knobs) without pytest.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np

from . import faults
from .breaker import CircuitBreaker
from .retry import RetryPolicy


def _write_demo_znn(path: str, fin: int = 4, hidden: int = 3,
                    classes: int = 2, seed: int = 7) -> None:
    """A tiny deterministic fc(tanh)+fc+softmax model — enough layers
    to exercise the full forward without slow jit compiles.  Committed
    through the real atomic publish (manifest + ``artifact.bitflip``
    chaos site), so corruption drills can rot it deterministically."""
    from ..export import ACT, KIND, _commit_znn, _pack_layer, \
        _write_header
    gen = np.random.default_rng(seed)
    w1 = gen.standard_normal((fin, hidden)).astype(np.float32)
    b1 = gen.standard_normal(hidden).astype(np.float32)
    w2 = gen.standard_normal((hidden, classes)).astype(np.float32)
    with open(path + ".tmp", "wb") as fh:
        _write_header(fh, 3)
        _pack_layer(fh, KIND["fc"], ACT["tanh"], [fin, hidden], w1, b1)
        _pack_layer(fh, KIND["fc"], ACT["linear"], [hidden, classes], w2)
        _pack_layer(fh, KIND["softmax"], 0, [])
    _commit_znn(path)


def _post(url: str, payload: dict, timeout: float = 30.0):
    """(status, body) — errors become their status code, a connection
    hang becomes the invariant failure it is."""
    req = urllib.request.Request(
        url + "predict", json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _health(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url + "healthz", timeout=timeout) as r:
        return json.loads(r.read())


def _admin_reload(url: str, model: str, timeout: float = 60.0):
    """(status, body) of a synchronous ``POST /admin/reload``."""
    req = urllib.request.Request(
        url + "admin/reload",
        json.dumps({"model": model, "wait": True}).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _reload_scenario(args) -> int:
    """``--scenario reload`` — the corruption→rollback drill
    (docs/durability.md): serve v1, hot-reload a bit-rotted v2 (the
    ``artifact.bitflip`` fault site fires during its export, so the rot
    is deterministic) and assert the rollback contract — generation
    unchanged, the OLD model still answering 200s with identical bytes,
    ``/healthz`` reporting the failed outcome — then land a good v3 and
    assert the zero-downtime swap."""
    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        v1 = os.path.join(tmp, "v1.znn")
        _write_demo_znn(v1)
        engine = ServingEngine(v1, backend="jax", buckets=(1, 2))
        server = ServingServer(engine, max_wait_ms=1.0).start()
        try:
            status, body, _ = _post(server.url, {"inputs": x})
            y0 = body.get("outputs")
            if status != 200:
                bad.append(f"baseline predict got {status}")
            # v2 rots as it lands on disk: one flipped byte under a
            # live manifest — exactly what verify-on-load must catch
            v2 = os.path.join(tmp, "v2.znn")
            plan = faults.FaultPlan([faults.FaultSpec(
                "artifact.bitflip", times=1,
                message="chaos: storage rot on the new artifact")],
                seed=7)
            with plan:
                _write_demo_znn(v2, seed=11)
            if plan.snapshot().get("artifact.bitflip:error", 0) != 1:
                bad.append("bitflip fault never fired — v2 is clean "
                           "and the drill proves nothing")
            status, rec = _admin_reload(server.url, v2)
            last = (rec.get("last_reload") or {})
            print(json.dumps({"phase": "corrupt-reload",
                              "status": status, "reload": last,
                              "generation": rec.get("model_generation")}))
            if last.get("outcome") != "verify_failed":
                bad.append(f"corrupt reload outcome "
                           f"{last.get('outcome')!r}, expected "
                           f"'verify_failed'")
            if rec.get("model_generation") != 1:
                bad.append(f"generation moved to "
                           f"{rec.get('model_generation')} on a failed "
                           f"reload")
            for i in range(args.requests):
                status, body, _ = _post(server.url, {"inputs": x})
                if status != 200:
                    bad.append(f"post-rollback request {i} got {status}")
                elif body.get("outputs") != y0:
                    bad.append(f"post-rollback request {i} answered "
                               f"with different bytes — generations "
                               f"mixed")
            health = _health(server.url)
            if health["status"] != "ok":
                bad.append(f"healthz {health['status']!r} after a "
                           f"rolled-back reload, expected 'ok'")
            if (health.get("last_reload") or {}).get("outcome") \
                    != "verify_failed":
                bad.append("healthz does not report the failed reload")
            # a good artifact swaps with zero downtime
            v3 = os.path.join(tmp, "v3.znn")
            _write_demo_znn(v3, seed=23)
            status, rec = _admin_reload(server.url, v3)
            last = (rec.get("last_reload") or {})
            print(json.dumps({"phase": "good-reload", "status": status,
                              "reload": last,
                              "generation": rec.get("model_generation")}))
            if last.get("outcome") != "ok" \
                    or rec.get("model_generation") != 2:
                bad.append(f"good reload did not swap: {last}")
            status, body, _ = _post(server.url, {"inputs": x})
            if status != 200:
                bad.append(f"post-swap predict got {status}")
            elif body.get("outputs") == y0:
                bad.append("post-swap outputs identical to v1 — the "
                           "new weights never took")
            print(json.dumps({
                "scenario": "reload", "ok": not bad, "violations": bad,
                "engine": {k: v for k, v in engine.metrics().items()
                           if k in ("generation", "reloads")}}))
        finally:
            server.stop()
            engine.close()
    return 1 if bad else 0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="znicz_tpu chaos",
        description="smoke the serving stack under an injected "
                    "engine.forward fault (see docs/resilience.md)")
    p.add_argument("--model", default=None,
                   help=".znn to serve (default: a tiny built-in demo "
                        "model)")
    p.add_argument("--plan", default=None,
                   help="fault plan: inline JSON or @file (default: a "
                        "canned engine.forward fault that exhausts "
                        "after tripping the breaker)")
    p.add_argument("--requests", type=int, default=8,
                   help="requests to fire while the fault is live")
    p.add_argument("--breaker-threshold", type=int, default=2)
    p.add_argument("--cooldown-s", type=float, default=1.0)
    p.add_argument("--retry-attempts", type=int, default=2)
    p.add_argument("--scenario", default="breaker",
                   choices=("breaker", "reload"),
                   help="breaker: the engine-fault degradation arc "
                        "(default); reload: hot-reload a corrupted "
                        "artifact and assert rollback + zero downtime "
                        "(docs/durability.md)")
    args = p.parse_args(argv)
    if args.scenario == "reload":
        return _reload_scenario(args)

    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    tmp = None
    model = args.model
    if model is None:
        tmp = tempfile.TemporaryDirectory(prefix="znicz_chaos_")
        model = os.path.join(tmp.name, "demo.znn")
        _write_demo_znn(model)

    if args.plan is not None:
        plan = faults.parse_plan(args.plan)
    else:
        # fail exactly long enough to trip the breaker through the
        # retries, then recover — the full closed→open→half_open→
        # closed arc (each pre-trip request burns retry_attempts
        # firings; the half-open probe must find the fault gone)
        times = args.retry_attempts * args.breaker_threshold
        plan = faults.FaultPlan([faults.FaultSpec(
            "engine.forward", times=times,
            message="chaos: injected transient device fault")], seed=7)
    faults.install(plan)

    engine = ServingEngine(
        model, backend="jax", buckets=(1, 2),
        retry=RetryPolicy(max_attempts=args.retry_attempts,
                          base_delay_s=0.01, max_delay_s=0.05),
        breaker=CircuitBreaker(failure_threshold=args.breaker_threshold,
                               cooldown_s=args.cooldown_s))
    server = ServingServer(engine, max_wait_ms=1.0).start()
    x = [[0.1, -0.2, 0.3, 0.4]]
    codes, bad = [], []
    try:
        for i in range(args.requests):
            status, body, headers = _post(server.url, {"inputs": x})
            health = _health(server.url)["status"]
            codes.append(status)
            if status not in (200, 503):
                bad.append(f"request {i}: unexpected status {status} "
                           f"({body.get('error')})")
            if status == 503 and "Retry-After" not in headers:
                bad.append(f"request {i}: 503 without Retry-After")
            print(json.dumps({"request": i, "status": status,
                              "health": health,
                              "breaker": engine.breaker.state}))
        # fault plan exhausted by now: wait out the cooldown, then one
        # request must probe half-open and close the circuit
        time.sleep(args.cooldown_s + 0.1)
        status, body, _ = _post(server.url, {"inputs": x})
        health = _health(server.url)
        print(json.dumps({"request": "post-recovery", "status": status,
                          "health": health["status"],
                          "breaker": engine.breaker.state}))
        if status != 200:
            bad.append(f"post-recovery request got {status}, "
                       f"expected 200")
        if engine.breaker.state != "closed":
            bad.append(f"breaker did not close after recovery "
                       f"(state={engine.breaker.state})")
        if health["status"] != "ok":
            bad.append(f"healthz stuck at {health['status']!r} "
                       f"after recovery")
        m = engine.breaker.metrics()
        summary = {"codes": codes, "fired": plan.snapshot(),
                   "breaker": m, "engine": {
                       k: v for k, v in engine.metrics().items()
                       if k in ("forward_calls", "forward_failures",
                                "fallback_calls", "retries")},
                   "ok": not bad, "violations": bad}
        print(json.dumps(summary))
    finally:
        faults.uninstall(plan)
        server.stop()
        engine.close()
        if tmp is not None:
            tmp.cleanup()
    if bad:
        return 1
    if m["trips"] < 1:
        print(json.dumps({"ok": False, "violations":
                          ["fault never tripped the breaker — plan "
                           "too weak for the configured threshold"]}))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
