"""``python -m znicz_tpu chaos`` — serving-under-fault smoke mode.

Boots the real HTTP serving stack (engine + micro-batcher + server)
under a canned :class:`~.faults.FaultPlan`, drives traffic through the
whole breaker lifecycle, and verifies the graceful-degradation
contract end to end:

* with a persistent ``engine.forward`` fault every request still
  resolves — native-fallback 200 or 503 + Retry-After, never a raw 500
  and never a hang;
* ``/healthz`` leaves ``ok`` while the circuit is open (``degraded`` /
  ``open``);
* once the fault clears, a half-open probe closes the breaker and
  ``/healthz`` returns to ``ok``.

A second drill, ``--scenario reload``, smokes the durability layer
(docs/durability.md): a hot reload of a deterministically bit-rotted
artifact must roll back — verify fails, the generation stays put, the
old model keeps answering 200s with identical bytes — and a subsequent
good artifact must swap with zero downtime.

The third drill, ``--scenario promote``, is the closed-loop acceptance
(docs/promotion.md): a stand-in trainer keeps committing fresh
candidate ``.znn`` artifacts through the real atomic export path while
live traffic flows, and a :class:`~znicz_tpu.promotion.controller.
PromotionController` drives each one through verify → export → canary
reload → SLO watch — under injected transient faults at
``engine.forward``, ``promotion.export`` and ``promotion.slo_probe``
— then a deliberately-regressed candidate (it canaries clean but
latency-regresses under traffic, injected at ``engine.forward``) must
be auto-rolled-back within the SLO window.  Asserted: zero non-200
``/predict`` answers across the whole run, ≥N promotions landed, the
rollback restored the previous generation's exact bytes, and the
promotion ledger records every transition.

Exit code 0 when every invariant holds — tools/chaos_smoke.sh wires
this into CI-ish usage.  The same ``FaultPlan`` mechanism drives the
pytest ``chaos`` marker; this mode exists so an operator can smoke a
REAL server (their model, their knobs) without pytest.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import urllib.error
import urllib.request

import numpy as np

from . import faults
from .breaker import CircuitBreaker
from .retry import RetryPolicy


def _write_demo_znn(path: str, fin: int = 4, hidden: int = 3,
                    classes: int = 2, seed: int = 7) -> None:
    """A tiny deterministic fc(tanh)+fc+softmax model — enough layers
    to exercise the full forward without slow jit compiles.  Committed
    through the real atomic publish (manifest + ``artifact.bitflip``
    chaos site), so corruption drills can rot it deterministically."""
    from ..export import ACT, KIND, _commit_znn, _pack_layer, \
        _write_header
    gen = np.random.default_rng(seed)
    w1 = gen.standard_normal((fin, hidden)).astype(np.float32)
    b1 = gen.standard_normal(hidden).astype(np.float32)
    w2 = gen.standard_normal((hidden, classes)).astype(np.float32)
    with open(path + ".tmp", "wb") as fh:
        _write_header(fh, 3)
        _pack_layer(fh, KIND["fc"], ACT["tanh"], [fin, hidden], w1, b1)
        _pack_layer(fh, KIND["fc"], ACT["linear"], [hidden, classes], w2)
        _pack_layer(fh, KIND["softmax"], 0, [])
    _commit_znn(path)


def _post(url: str, payload: dict, timeout: float = 30.0):
    """(status, body) — errors become their status code, a connection
    hang becomes the invariant failure it is."""
    req = urllib.request.Request(
        url + "predict", json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _health(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url + "healthz", timeout=timeout) as r:
        return json.loads(r.read())


def _admin_reload(url: str, model: str, timeout: float = 60.0):
    """(status, body) of a synchronous ``POST /admin/reload``."""
    req = urllib.request.Request(
        url + "admin/reload",
        json.dumps({"model": model, "wait": True}).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _reload_scenario(args) -> int:
    """``--scenario reload`` — the corruption→rollback drill
    (docs/durability.md): serve v1, hot-reload a bit-rotted v2 (the
    ``artifact.bitflip`` fault site fires during its export, so the rot
    is deterministic) and assert the rollback contract — generation
    unchanged, the OLD model still answering 200s with identical bytes,
    ``/healthz`` reporting the failed outcome — then land a good v3 and
    assert the zero-downtime swap."""
    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        v1 = os.path.join(tmp, "v1.znn")
        _write_demo_znn(v1)
        engine = ServingEngine(v1, backend="jax", buckets=(1, 2))
        server = ServingServer(engine, max_wait_ms=1.0).start()
        try:
            status, body, _ = _post(server.url, {"inputs": x})
            y0 = body.get("outputs")
            if status != 200:
                bad.append(f"baseline predict got {status}")
            # v2 rots as it lands on disk: one flipped byte under a
            # live manifest — exactly what verify-on-load must catch
            v2 = os.path.join(tmp, "v2.znn")
            plan = faults.FaultPlan([faults.FaultSpec(
                "artifact.bitflip", times=1,
                message="chaos: storage rot on the new artifact")],
                seed=7)
            with plan:
                _write_demo_znn(v2, seed=11)
            if plan.snapshot().get("artifact.bitflip:error", 0) != 1:
                bad.append("bitflip fault never fired — v2 is clean "
                           "and the drill proves nothing")
            status, rec = _admin_reload(server.url, v2)
            last = (rec.get("last_reload") or {})
            print(json.dumps({"phase": "corrupt-reload",
                              "status": status, "reload": last,
                              "generation": rec.get("model_generation")}))
            if last.get("outcome") != "verify_failed":
                bad.append(f"corrupt reload outcome "
                           f"{last.get('outcome')!r}, expected "
                           f"'verify_failed'")
            if rec.get("model_generation") != 1:
                bad.append(f"generation moved to "
                           f"{rec.get('model_generation')} on a failed "
                           f"reload")
            for i in range(args.requests):
                status, body, _ = _post(server.url, {"inputs": x})
                if status != 200:
                    bad.append(f"post-rollback request {i} got {status}")
                elif body.get("outputs") != y0:
                    bad.append(f"post-rollback request {i} answered "
                               f"with different bytes — generations "
                               f"mixed")
            health = _health(server.url)
            if health["status"] != "ok":
                bad.append(f"healthz {health['status']!r} after a "
                           f"rolled-back reload, expected 'ok'")
            if (health.get("last_reload") or {}).get("outcome") \
                    != "verify_failed":
                bad.append("healthz does not report the failed reload")
            # a good artifact swaps with zero downtime
            v3 = os.path.join(tmp, "v3.znn")
            _write_demo_znn(v3, seed=23)
            status, rec = _admin_reload(server.url, v3)
            last = (rec.get("last_reload") or {})
            print(json.dumps({"phase": "good-reload", "status": status,
                              "reload": last,
                              "generation": rec.get("model_generation")}))
            if last.get("outcome") != "ok" \
                    or rec.get("model_generation") != 2:
                bad.append(f"good reload did not swap: {last}")
            status, body, _ = _post(server.url, {"inputs": x})
            if status != 200:
                bad.append(f"post-swap predict got {status}")
            elif body.get("outputs") == y0:
                bad.append("post-swap outputs identical to v1 — the "
                           "new weights never took")
            print(json.dumps({
                "scenario": "reload", "ok": not bad, "violations": bad,
                "engine": {k: v for k, v in engine.metrics().items()
                           if k in ("generation", "reloads")}}))
        finally:
            server.stop()
            engine.close()
    return 1 if bad else 0


def _promote_scenario(args) -> int:
    """``--scenario promote`` — train-while-serving through N
    promotions with fault injection plus one deliberately-regressed
    candidate; the zero-500 / verified-rollback acceptance of
    docs/promotion.md."""
    import collections
    import threading

    from ..promotion import (DirectorySource, EngineTarget,
                             PromotionController, SLOPolicy)
    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    bad: list[str] = []
    x = [[0.1, -0.2, 0.3, 0.4]]
    with tempfile.TemporaryDirectory(prefix="znicz_chaos_") as tmp:
        cands = os.path.join(tmp, "candidates")
        deploy = os.path.join(tmp, "deploy")
        os.makedirs(cands)
        v0 = os.path.join(tmp, "v0.znn")
        _write_demo_znn(v0, seed=5)
        engine = ServingEngine(v0, backend="jax", buckets=(1, 2))
        server = ServingServer(engine, max_wait_ms=1.0).start()
        policy = SLOPolicy(
            window_s=args.watch_s,
            probe_interval_s=max(0.1, args.watch_s / 6.0),
            max_p99_ms=args.max_p99_ms, max_error_rate=0.05,
            min_samples=3)
        controller = PromotionController(
            DirectorySource(cands), EngineTarget(server=server),
            deploy_dir=deploy, policy=policy, poll_interval_s=0.1,
            max_consecutive_failures=3)
        stop = threading.Event()
        codes: list[int] = []
        mu = threading.Lock()

        def traffic():
            # continuous live traffic for the whole run — the zero-500
            # assertion is over every answer this loop collects
            while not stop.is_set():
                try:
                    status, _body, _h = _post(server.url,
                                              {"inputs": x},
                                              timeout=30.0)
                except Exception:
                    status = -1        # hang/conn drop = the failure
                with mu:
                    codes.append(status)
                stop.wait(0.01)

        thread = threading.Thread(target=traffic, daemon=True)
        thread.start()
        try:
            # let the first jit compile land so the SLO baseline sees
            # steady-state latency, not the cold start
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                with mu:
                    if len(codes) >= 5:
                        break
                time.sleep(0.05)
            outcomes = []
            for k in range(args.promotions):
                # the stand-in trainer: a fresh candidate through the
                # real atomic export path, promoted under transient
                # faults at every new seam (each absorbed by a retry
                # tier, so the promotion still lands)
                plan = faults.FaultPlan([
                    faults.FaultSpec("engine.forward", times=1,
                                     message="chaos: transient device "
                                             "fault"),
                    faults.FaultSpec("promotion.export", times=1,
                                     message="chaos: export blip"),
                    faults.FaultSpec("promotion.slo_probe", times=1,
                                     message="chaos: probe blip")],
                    seed=100 + k)
                with plan:
                    _write_demo_znn(os.path.join(cands,
                                                 f"cand{k + 1}.znn"),
                                    seed=30 + k)
                    outcome = controller.run_once()
                outcomes.append(outcome)
                print(json.dumps({"phase": f"promotion-{k + 1}",
                                  "outcome": outcome,
                                  "generation": engine.generation,
                                  "fired": plan.snapshot()}))
                if outcome != "promoted":
                    bad.append(f"candidate {k + 1} outcome {outcome!r},"
                               f" expected 'promoted'")
            status, body, _ = _post(server.url, {"inputs": x})
            y_good = body.get("outputs")
            gen_good = engine.generation
            if status != 200:
                bad.append(f"post-promotions probe got {status}")
            # the regressed candidate: canaries clean (well-formed,
            # finite) but every live forward slows by bad_latency_s —
            # the SLO watch must catch it and roll back while the
            # previous artifact still sits in the deploy dir
            _write_demo_znn(os.path.join(cands, "cand-bad.znn"),
                            seed=99)
            plan = faults.FaultPlan([faults.FaultSpec(
                "engine.forward", kind="latency",
                latency_s=args.bad_latency_s,
                message="chaos: regressed candidate")], seed=7)
            with plan:
                outcome = controller.run_once()
            print(json.dumps({"phase": "bad-candidate",
                              "outcome": outcome,
                              "generation": engine.generation,
                              "fired": plan.snapshot()}))
            if outcome != "rolled_back":
                bad.append(f"bad candidate outcome {outcome!r}, "
                           f"expected 'rolled_back'")
            status, body, _ = _post(server.url, {"inputs": x})
            if status != 200:
                bad.append(f"post-rollback probe got {status}")
            elif body.get("outputs") != y_good:
                bad.append("post-rollback outputs differ from the "
                           "blessed generation — rollback did not "
                           "restore the previous bytes")
            if engine.generation != gen_good + 2:
                bad.append(f"generation {engine.generation} after "
                           f"rollback, expected {gen_good + 2} "
                           f"(bad swap + rollback swap)")
            health = _health(server.url)
            promo = health.get("promotion") or {}
            if promo.get("state") != "rolled_back" \
                    or promo.get("last_outcome") != "rolled_back":
                bad.append(f"healthz promotion block does not report "
                           f"the rollback: {promo}")
        finally:
            stop.set()
            thread.join(10.0)
            server.stop()
            engine.close()
        with mu:
            answered = list(codes)
        non200 = collections.Counter(c for c in answered if c != 200)
        if non200:
            bad.append(f"non-200 answers under promotion chaos: "
                       f"{dict(non200)} of {len(answered)}")
        # the ledger is the audit trail: every candidate must show its
        # state transitions and exactly the expected outcomes
        entries = controller.ledger.entries()
        outs = [e for e in entries if e.get("event") == "outcome"]
        n_promoted = sum(1 for e in outs if e["outcome"] == "promoted")
        n_rolled = sum(1 for e in outs if e["outcome"] == "rolled_back")
        if n_promoted != args.promotions or n_rolled != 1:
            bad.append(f"ledger outcomes: {n_promoted} promoted / "
                       f"{n_rolled} rolled_back, expected "
                       f"{args.promotions} / 1")
        states = {e.get("state") for e in entries
                  if e.get("event") == "state"}
        for want in ("verifying", "exporting", "canarying", "watching"):
            if want not in states:
                bad.append(f"ledger never recorded the {want!r} state")
        if not any(e.get("event") == "rollback" for e in entries):
            bad.append("ledger has no rollback event")
        print(json.dumps({
            "scenario": "promote", "ok": not bad, "violations": bad,
            "requests": len(answered), "outcomes": outcomes + [outcome],
            "promotion": controller.status(),
            "ledger_events": len(entries)}))
    return 1 if bad else 0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="znicz_tpu chaos",
        description="smoke the serving stack under an injected "
                    "engine.forward fault (see docs/resilience.md)")
    p.add_argument("--model", default=None,
                   help=".znn to serve (default: a tiny built-in demo "
                        "model)")
    p.add_argument("--plan", default=None,
                   help="fault plan: inline JSON or @file (default: a "
                        "canned engine.forward fault that exhausts "
                        "after tripping the breaker)")
    p.add_argument("--requests", type=int, default=8,
                   help="requests to fire while the fault is live")
    p.add_argument("--breaker-threshold", type=int, default=2)
    p.add_argument("--cooldown-s", type=float, default=1.0)
    p.add_argument("--retry-attempts", type=int, default=2)
    p.add_argument("--scenario", default="breaker",
                   choices=("breaker", "reload", "promote"),
                   help="breaker: the engine-fault degradation arc "
                        "(default); reload: hot-reload a corrupted "
                        "artifact and assert rollback + zero downtime "
                        "(docs/durability.md); promote: the closed "
                        "loop — N promotions under fault injection "
                        "plus a regressed candidate auto-rolled-back "
                        "by the SLO watch (docs/promotion.md)")
    p.add_argument("--promotions", type=int, default=3,
                   help="promote: good candidates to drive through "
                        "the loop before the regressed one")
    p.add_argument("--watch-s", type=float, default=1.2,
                   help="promote: SLO watch window per promotion")
    p.add_argument("--max-p99-ms", type=float, default=50.0,
                   help="promote: p99 latency objective the regressed "
                        "candidate must breach")
    p.add_argument("--bad-latency-s", type=float, default=0.08,
                   help="promote: per-forward latency injected while "
                        "the regressed candidate serves")
    args = p.parse_args(argv)
    if args.scenario == "reload":
        return _reload_scenario(args)
    if args.scenario == "promote":
        return _promote_scenario(args)

    from ..serving.engine import ServingEngine
    from ..serving.server import ServingServer

    tmp = None
    model = args.model
    if model is None:
        tmp = tempfile.TemporaryDirectory(prefix="znicz_chaos_")
        model = os.path.join(tmp.name, "demo.znn")
        _write_demo_znn(model)

    if args.plan is not None:
        plan = faults.parse_plan(args.plan)
    else:
        # fail exactly long enough to trip the breaker through the
        # retries, then recover — the full closed→open→half_open→
        # closed arc (each pre-trip request burns retry_attempts
        # firings; the half-open probe must find the fault gone)
        times = args.retry_attempts * args.breaker_threshold
        plan = faults.FaultPlan([faults.FaultSpec(
            "engine.forward", times=times,
            message="chaos: injected transient device fault")], seed=7)
    faults.install(plan)

    engine = ServingEngine(
        model, backend="jax", buckets=(1, 2),
        retry=RetryPolicy(max_attempts=args.retry_attempts,
                          base_delay_s=0.01, max_delay_s=0.05),
        breaker=CircuitBreaker(failure_threshold=args.breaker_threshold,
                               cooldown_s=args.cooldown_s))
    server = ServingServer(engine, max_wait_ms=1.0).start()
    x = [[0.1, -0.2, 0.3, 0.4]]
    codes, bad = [], []
    try:
        for i in range(args.requests):
            status, body, headers = _post(server.url, {"inputs": x})
            health = _health(server.url)["status"]
            codes.append(status)
            if status not in (200, 503):
                bad.append(f"request {i}: unexpected status {status} "
                           f"({body.get('error')})")
            if status == 503 and "Retry-After" not in headers:
                bad.append(f"request {i}: 503 without Retry-After")
            print(json.dumps({"request": i, "status": status,
                              "health": health,
                              "breaker": engine.breaker.state}))
        # fault plan exhausted by now: wait out the cooldown, then one
        # request must probe half-open and close the circuit
        time.sleep(args.cooldown_s + 0.1)
        status, body, _ = _post(server.url, {"inputs": x})
        health = _health(server.url)
        print(json.dumps({"request": "post-recovery", "status": status,
                          "health": health["status"],
                          "breaker": engine.breaker.state}))
        if status != 200:
            bad.append(f"post-recovery request got {status}, "
                       f"expected 200")
        if engine.breaker.state != "closed":
            bad.append(f"breaker did not close after recovery "
                       f"(state={engine.breaker.state})")
        if health["status"] != "ok":
            bad.append(f"healthz stuck at {health['status']!r} "
                       f"after recovery")
        m = engine.breaker.metrics()
        summary = {"codes": codes, "fired": plan.snapshot(),
                   "breaker": m, "engine": {
                       k: v for k, v in engine.metrics().items()
                       if k in ("forward_calls", "forward_failures",
                                "fallback_calls", "retries")},
                   "ok": not bad, "violations": bad}
        print(json.dumps(summary))
    finally:
        faults.uninstall(plan)
        server.stop()
        engine.close()
        if tmp is not None:
            tmp.cleanup()
    if bad:
        return 1
    if m["trips"] < 1:
        print(json.dumps({"ok": False, "violations":
                          ["fault never tripped the breaker — plan "
                           "too weak for the configured threshold"]}))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
