"""Fault injection, retry/backoff, and circuit breaking.

The robustness half of the serving story (PR 1 shipped backpressure;
this package ships degradation): preemption, relay drops, and transient
device errors are the steady state on shared TPU fleets, so every layer
that talks to a device, the filesystem, or another process goes through
one of three small primitives:

* :mod:`faults`  — seeded deterministic fault injection at named sites
  (``engine.forward``, ``checkpoint.save``, ``relay.connect``, ...),
  activated per-process or via ``$ZNICZ_FAULT_PLAN``; pytest ``chaos``
  tests and ``python -m znicz_tpu chaos`` share it.
* :mod:`retry`   — bounded attempts, exponential backoff + jitter,
  per-attempt timeout, transient-vs-deterministic classifier.
* :mod:`breaker` — circuit breaker (closed→open→half_open→closed) with
  :class:`~breaker.EngineUnavailable` carrying Retry-After for fronts.
* :mod:`overload` — overload defense in depth: end-to-end
  :class:`~overload.Deadline` propagation, the process-wide
  :class:`~overload.RetryBudget`, :class:`~overload.HedgePolicy` for
  hedged replica dispatch, and the :class:`~overload.CoDelShedder`
  adaptive admission ladder (docs/resilience.md "Overload defense").

See docs/resilience.md for the knob reference and degradation matrix.
"""

from .breaker import CircuitBreaker, EngineUnavailable
from .faults import FaultInjected, FaultPlan, FaultSpec, inject
from .overload import (CoDelShedder, Deadline, DeadlineExceeded,
                       DoomedDeadline, Draining, EarlyReject,
                       HedgePolicy, RetryBudget, Shed)
from .retry import AttemptTimeout, RetryPolicy, default_transient

__all__ = ["AttemptTimeout", "CircuitBreaker", "CoDelShedder",
           "Deadline", "DeadlineExceeded", "DoomedDeadline",
           "Draining", "EarlyReject", "EngineUnavailable",
           "FaultInjected", "FaultPlan", "FaultSpec", "HedgePolicy",
           "RetryBudget", "RetryPolicy", "Shed", "default_transient",
           "inject"]
