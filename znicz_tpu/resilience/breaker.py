"""Circuit breaker: stop hammering a failing dependency, probe for
recovery, degrade gracefully in between.

The serving problem this solves (ISSUE motivation): one flaky device
made every ``ServingEngine.predict`` fail forever while ``/healthz``
kept answering "ok".  With a breaker, K consecutive forward failures
OPEN the circuit — requests stop paying the retry+failure latency and
route to the degraded path (native CPU fallback, or 503 + Retry-After)
— and after ``cooldown_s`` a single HALF-OPEN probe is let through; its
success closes the circuit, its failure re-arms the cooldown.  The
state machine is the clipper/triton-style serving pattern PAPERS.md
catalogues, sized down to one in-process dependency.

States: ``closed`` (normal), ``open`` (failing, cooling down),
``half_open`` (cooldown elapsed, probe in flight or awaited).
"""

from __future__ import annotations

import threading
import time

from ..telemetry.registry import REGISTRY

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

_transitions = REGISTRY.counter(
    "breaker_transitions_total",
    "circuit breaker state transitions (closed→open is a trip, "
    "open→half_open a probe grant, half_open→closed a recovery)")


def _note_transition(old: str, new: str) -> None:
    """Registry event for one state change — called OUTSIDE the
    breaker's lock (the registry has its own; never nest them)."""
    if old != new:
        _transitions.inc(**{"from": old, "to": new})


class EngineUnavailable(RuntimeError):
    """The protected dependency cannot serve and no fallback exists.
    Carries ``retry_after`` (seconds) so fronts can answer
    503 + Retry-After instead of hanging or 500ing."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = max(1, int(round(retry_after)))


class CircuitBreaker:
    """Thread-safe closed→open→half_open→closed state machine.

    Protocol (the protected caller drives it):

    * ``allow()`` before an attempt — False means "don't touch the
      dependency, degrade now".  When open and the cooldown has
      elapsed it grants exactly ONE in-flight half-open probe.
    * ``record_success()`` / ``record_failure()`` after the attempt.
      Only attempts ``allow()`` approved should be recorded.
    * ``abandon()`` when an approved attempt never actually exercised
      the dependency (e.g. a non-retryable input error raised before
      the call) — frees the probe slot without changing state.
    """

    def __init__(self, failure_threshold: int = 5,
                 cooldown_s: float = 30.0, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, "
                             f"got {failure_threshold}")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at: float | None = None
        self._probe_inflight = False
        self._probe_owner: int | None = None   # thread ident of holder
        self._trips = 0          # closed/half_open → open transitions
        self._probes = 0         # half-open attempts granted

    # -- protocol ---------------------------------------------------------
    def allow(self) -> bool:
        old = None
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                old = self._state
                self._state = HALF_OPEN       # cooldown over: probe time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            self._probe_owner = threading.get_ident()
            self._probes += 1
        if old is not None:
            _note_transition(old, HALF_OPEN)
        return True

    def record_success(self) -> None:
        with self._lock:
            old = self._state
            self._state = CLOSED
            self._consecutive = 0
            self._probe_inflight = False
            self._probe_owner = None
            self._opened_at = None
        _note_transition(old, CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == OPEN:
                return   # a straggler admitted before the trip: the
                #          circuit is already open, don't re-arm the
                #          cooldown or double-count the trip
            if self._state == CLOSED:
                self._consecutive += 1
                if self._consecutive < self.failure_threshold:
                    return
            old = self._state
            self._state = OPEN               # trip, or failed probe
            self._opened_at = self._clock()
            self._probe_inflight = False
            self._probe_owner = None
            self._trips += 1
        _note_transition(old, OPEN)

    def trip(self) -> None:
        """Force the circuit open on an EXTERNAL verdict (e.g. the
        fleet tier's gray-failure demotion: probes green, real
        predicts sick — the failure count never reaches the
        threshold because transport-wise nothing failed).  Cooldown
        and the single half-open probe apply exactly as for a
        threshold trip, so recovery rides the existing path."""
        with self._lock:
            if self._state == OPEN:
                return
            old = self._state
            self._state = OPEN
            self._opened_at = self._clock()
            self._probe_inflight = False
            self._probe_owner = None
            self._trips += 1
        _note_transition(old, OPEN)

    def abandon(self) -> None:
        with self._lock:
            # only the thread HOLDING the half-open probe may free the
            # slot — a straggler admitted pre-trip that errors out must
            # not release someone else's in-flight probe (which would
            # admit a second concurrent probe)
            if self._probe_owner == threading.get_ident():
                self._probe_inflight = False
                self._probe_owner = None

    # -- introspection ----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            if self._state == OPEN and \
                    self._clock() - self._opened_at >= self.cooldown_s:
                return HALF_OPEN             # probe available, not taken
            return self._state

    def retry_after(self) -> float:
        """Seconds until a probe could be admitted (>= 1 for headers)."""
        with self._lock:
            if self._state == CLOSED or self._opened_at is None:
                return 1.0
            left = self.cooldown_s - (self._clock() - self._opened_at)
        return max(1.0, left)

    def metrics(self) -> dict:
        st = self.state                      # resolves elapsed cooldown
        with self._lock:
            return {"state": st, "trips": self._trips,
                    "probes": self._probes,
                    "consecutive_failures": self._consecutive,
                    "failure_threshold": self.failure_threshold,
                    "cooldown_s": self.cooldown_s}
