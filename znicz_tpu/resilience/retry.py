"""Reusable retry policy: bounded attempts, exponential backoff with
deterministic jitter, optional per-attempt timeout, and an exception
classifier separating transient faults (device hiccup, relay drop,
filesystem blip — retry) from deterministic bugs (bad geometry, type
errors — fail immediately; retrying a ValueError just repeats it).

Users: ``ServingEngine`` (transient device errors around the jitted
forward), ``CheckpointRecovery.save/resume`` (snapshot I/O), and
``parallel.distributed.initialize`` (coordinator connect).
"""

from __future__ import annotations

import random
import threading
import time

from ..telemetry.registry import REGISTRY
from . import overload

_retry_attempts = REGISTRY.counter(
    "retry_attempts_total",
    "retries performed by RetryPolicy.call (first attempts are not "
    "counted), labeled by the retried callable")


class AttemptTimeout(TimeoutError):
    """A single attempt exceeded the policy's per-attempt budget."""


def default_transient(exc: BaseException) -> bool:
    """Default classifier: programming/shape errors are deterministic —
    retrying cannot help and hides the bug from the caller (the serving
    front maps them to 400, not 503).  A passed deadline is equally
    unretryable: the budget that ran out does not come back, and a
    retry would be exactly the doomed work deadline propagation
    exists to refuse.  Everything else (RuntimeError, OSError,
    jaxlib's XlaRuntimeError, injected faults, timeouts) is treated
    as possibly-transient."""
    return not isinstance(exc, (ValueError, TypeError, KeyError,
                                IndexError, AttributeError,
                                NotImplementedError, AssertionError,
                                overload.DeadlineExceeded,
                                overload.EarlyReject))


class RetryPolicy:
    """``call(fn, *args)`` with up to ``max_attempts`` tries.

    Backoff before attempt ``n`` (1-based retries) is
    ``min(max_delay_s, base_delay_s * 2**(n-1))`` scaled by a jitter
    factor drawn uniformly from ``[1-jitter, 1]`` — full-value sleeps
    synchronize retry storms across clients, which is exactly the
    thundering herd backoff exists to break.  The jitter stream is
    seeded per-policy, so tests replay the same schedule.

    ``attempt_timeout_s`` bounds ONE attempt by running it on a helper
    thread; on expiry the attempt counts as a transient
    :class:`AttemptTimeout` failure.  The abandoned thread is left to
    finish in the background (Python cannot safely kill it) — use only
    around calls that eventually return, like a slow collective or a
    hung filesystem write, where "stop waiting" is the required
    behavior and "stop computing" is impossible anyway.

    Overload defense (docs/resilience.md): with ``budget`` set (a
    process-wide :class:`~znicz_tpu.resilience.overload.RetryBudget`)
    every retry spends one token — empty bucket means the LAST error
    surfaces instead of another attempt, so a correlated failure
    cannot turn into a fleet-wide retry storm.  Independent of the
    budget, when the current request carries a deadline
    (:func:`~znicz_tpu.resilience.overload.current_deadline`), a
    retry whose backoff + observed attempt time cannot fit the
    remaining budget is refused as doomed work
    (``deadline_exceeded_total{stage="retry"}``).
    """

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, jitter: float = 0.5,
                 attempt_timeout_s: float | None = None,
                 retryable=default_transient, seed: int = 0,
                 sleep=time.sleep,
                 budget: "overload.RetryBudget | None" = None):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, "
                             f"got {max_attempts}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.attempt_timeout_s = attempt_timeout_s
        self.retryable = retryable
        self.budget = budget
        self._rng = random.Random(seed)
        self._sleep = sleep

    def backoff_s(self, retry_index: int) -> float:
        """Delay before retry ``retry_index`` (1-based), jittered."""
        raw = min(self.max_delay_s,
                  self.base_delay_s * (2.0 ** (retry_index - 1)))
        return raw * (1.0 - self.jitter * self._rng.random())

    def _attempt(self, fn, args, kwargs):
        if self.attempt_timeout_s is None:
            return fn(*args, **kwargs)
        box: dict = {}

        def runner():
            try:
                box["result"] = fn(*args, **kwargs)
            except BaseException as e:
                box["error"] = e

        t = threading.Thread(target=runner, daemon=True,
                             name="znicz-retry-attempt")
        t.start()
        t.join(self.attempt_timeout_s)
        if t.is_alive():
            raise AttemptTimeout(
                f"attempt exceeded {self.attempt_timeout_s}s")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def call(self, fn, *args, on_retry=None, **kwargs):
        """Run ``fn(*args, **kwargs)``; retries transient failures with
        backoff.  ``on_retry(attempt, exc)`` fires before each sleep
        (metrics hook).  Raises the LAST exception when attempts run
        out, and non-retryable exceptions immediately."""
        for attempt in range(1, self.max_attempts + 1):
            t0 = time.monotonic()
            try:
                result = self._attempt(fn, args, kwargs)
            except Exception as e:     # KeyboardInterrupt/SystemExit
                #                        always propagate unretried
                attempt_s = time.monotonic() - t0
                if attempt >= self.max_attempts or not self.retryable(e):
                    raise
                backoff = self.backoff_s(attempt)
                dl = overload.current_deadline()
                if dl is not None and dl.at is not None \
                        and dl.remaining_s() < backoff + attempt_s:
                    # the sleep + another attempt of the size just
                    # observed cannot fit the remaining budget: the
                    # retry is doomed work, surface the error now
                    overload.note_deadline("retry")
                    raise
                if self.budget is not None \
                        and not self.budget.try_spend():
                    # fleet-wide budget empty: retrying would amplify
                    # the correlated failure that drained it
                    raise
                _retry_attempts.inc(fn=getattr(fn, "__name__", "?"))
                if on_retry is not None:
                    on_retry(attempt, e)
                self._sleep(backoff)
            else:
                if self.budget is not None:
                    self.budget.on_success()
                return result

    def wrap(self, fn, on_retry=None):
        """Decorator form of :meth:`call`."""
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, on_retry=on_retry, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped
