"""Seeded, deterministic fault injection at named sites.

Serving heavy traffic on TPUs means preemption, relay drops, and
transient device errors are the steady state (ROADMAP north star;
SURVEY.md §5 — the reference's master/slave protocol existed largely to
survive lost slaves).  Testing the recovery machinery therefore needs a
way to *cause* those failures on demand, deterministically, in both
pytest (``-m chaos``) and the ``python -m znicz_tpu chaos`` smoke mode
— one mechanism, two drivers.

Instrumented code calls :func:`inject` with a site name::

    from znicz_tpu.resilience import faults
    faults.inject("engine.forward")

which is a near-free no-op until a :class:`FaultPlan` is installed
(explicitly, or via the ``ZNICZ_FAULT_PLAN`` environment variable —
inline JSON or ``@/path/to/plan.json``).  A plan is a list of
:class:`FaultSpec` entries; each spec matches one site and fires an
exception or an added latency with seeded pseudo-randomness, so a chaos
test replays bit-identically across runs.

Instrumented sites (grow this list as subsystems adopt injection):

=====================  ====================================================
``engine.forward``     ServingEngine's jitted JAX forward (per attempt —
                       retries re-trigger it; the native fallback path
                       deliberately does NOT pass through this site)
``batcher.dispatch``   MicroBatcher just before an engine call (latency
                       injection point for deadline/backpressure tests)
``checkpoint.save``    SnapshotterToFile.save (crash-during-checkpoint,
                       fired BEFORE any filesystem mutation)
``checkpoint.load``    SnapshotterToFile.load (corrupt/unreadable resume)
``checkpoint.write_torn``  inside SnapshotterToFile.save's torn window,
                       between the blob rename and the manifest rename
                       — an error fault dies torn (new blob, stale
                       manifest), a latency fault holds the window open
                       for the SIGKILL crash-consistency tests
``artifact.bitflip``   durability.chaos_bitflip, called on every
                       just-committed .znn/snapshot blob — an error
                       fault here is *interpreted*: one mid-file byte
                       is flipped in place (deterministic storage rot;
                       verify-on-load must quarantine + fall back)
``relay.connect``      parallel.distributed.initialize's coordinator
                       bootstrap (the reference's lost-master case)
``promotion.export``   PromotionController's export step (candidate →
                       deploy-dir .znn commit), per attempt — the
                       controller retries it as transient
``promotion.slo_probe``  each SLO watch-window probe (registry read or
                       /metrics scrape) in the promotion controller —
                       a flaky probe must be retried, never counted
                       as a breach
``replica.slow.<i>``   EngineReplicaSet dispatch to replica ``i`` (one
                       site per replica index) — a latency fault here
                       is the deterministic "one slow-but-not-sick
                       replica" the hedging drill keys on
                       (``chaos --scenario overload``)
``capture.append``     the serving traffic tap's request-path enqueue
                       (online.capture.CaptureLog.append) — the tap is
                       FAIL-OPEN: an error fault here must surface as
                       a counted capture_dropped_total{reason=error}
                       drop, never as a failed or delayed /predict
                       answer (``chaos --scenario online`` +
                       tests/test_online.py pin this)
``statestore.append``  the fleet control-plane journal's fsync'd write
                       (fleet.statestore.StateStore.append) — the
                       journal is FAIL-CLOSED for mutations: an error
                       fault here must refuse the admin mutation with
                       503 + Retry-After and mark the store degraded,
                       while reads and /predict keep serving
                       (tests/test_ha.py pins this)
=====================  ====================================================
"""

from __future__ import annotations

import builtins
import collections
import json
import os
import random
import threading
import time
import zlib
from dataclasses import dataclass, field

from ..telemetry.registry import REGISTRY

_injected = REGISTRY.counter(
    "faults_injected_total",
    "chaos faults actually fired, by site and kind (a fault plan's "
    "specs that skip/exhaust do not count)")


class FaultInjected(RuntimeError):
    """Default exception type raised by an ``error`` fault."""


@dataclass
class FaultSpec:
    """One fault rule.  ``site`` names the injection point; ``kind`` is
    ``"error"`` (raise) or ``"latency"`` (sleep ``latency_s``); ``p`` is
    the per-hit firing probability under the plan's seeded stream;
    ``after`` skips the first N hits and ``times`` caps total firings
    (``None`` = unlimited) — together they script "fails K times, then
    recovers", the breaker's half-open-probe scenario."""

    site: str
    kind: str = "error"
    p: float = 1.0
    times: int | None = None
    after: int = 0
    exc: str = "FaultInjected"
    message: str = "injected fault"
    latency_s: float = 0.0
    # per-spec runtime state (not part of the plan's identity)
    hits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in ("error", "latency"):
            raise ValueError(f"fault kind {self.kind!r}; expected "
                             f"'error' or 'latency'")
        if not 0.0 <= float(self.p) <= 1.0:
            raise ValueError(f"fault probability {self.p!r} not in [0,1]")

    def exception(self) -> BaseException:
        """The exception instance this spec raises — a builtin by name,
        else :class:`FaultInjected` (never an arbitrary import: plans
        come from env vars)."""
        cls = getattr(builtins, self.exc, None)
        if not (isinstance(cls, type) and issubclass(cls, BaseException)):
            cls = FaultInjected
        return cls(f"{self.message} [site={self.site}]")


class FaultPlan:
    """A seeded set of :class:`FaultSpec` rules plus firing stats.

    Deterministic: each spec draws from its own ``random.Random``
    stream keyed ``(plan seed, site crc32, spec index)``, so adding a
    spec never perturbs another's firing pattern.  Thread-safe — the
    serving path injects from many handler threads.

    Use as a context manager to install/uninstall around a test::

        with FaultPlan([FaultSpec("engine.forward", times=3)]):
            ...
    """

    def __init__(self, faults, seed: int = 0):
        self.seed = int(seed)
        self.faults = list(faults)
        self._lock = threading.Lock()
        self.stats = collections.Counter()        # f"{site}:{kind}" → n
        self._rngs = [
            random.Random((self.seed << 32)
                          ^ zlib.crc32(f.site.encode()) ^ i)
            for i, f in enumerate(self.faults)]

    # -- construction -----------------------------------------------------
    @classmethod
    def from_dict(cls, obj: dict) -> "FaultPlan":
        """``{"seed": 0, "faults": [{"site": ..., ...}, ...]}``."""
        return cls([FaultSpec(**spec) for spec in obj.get("faults", [])],
                   seed=obj.get("seed", 0))

    @classmethod
    def from_env(cls, var: str = "ZNICZ_FAULT_PLAN") -> "FaultPlan | None":
        """Plan from ``$ZNICZ_FAULT_PLAN`` — inline JSON, or a JSON file
        path prefixed ``@`` — or None when unset/empty."""
        raw = os.environ.get(var, "").strip()
        return parse_plan(raw) if raw else None

    # -- firing -----------------------------------------------------------
    def fire(self, site: str) -> None:
        """Apply every matching spec for one hit of ``site`` — sleeps
        for latency faults, raises for error faults."""
        delay, boom, fired = 0.0, None, []
        with self._lock:
            for spec, rng in zip(self.faults, self._rngs):
                if spec.site != site:
                    continue
                spec.hits += 1
                if spec.hits <= spec.after:
                    continue
                if spec.times is not None and spec.fired >= spec.times:
                    continue
                if spec.p < 1.0 and rng.random() >= spec.p:
                    continue
                spec.fired += 1
                self.stats[f"{site}:{spec.kind}"] += 1
                fired.append(spec.kind)
                if spec.kind == "latency":
                    delay += spec.latency_s
                elif boom is None:        # first error spec wins
                    boom = spec.exception()
        for kind in fired:       # registry event, outside the plan lock
            _injected.inc(site=site, kind=kind)
        if delay > 0.0:
            time.sleep(delay)
        if boom is not None:
            raise boom

    def snapshot(self) -> dict:
        """Firing stats keyed ``site:kind`` (for logs / chaos report)."""
        with self._lock:
            return dict(self.stats)

    # -- install/uninstall ------------------------------------------------
    def __enter__(self) -> "FaultPlan":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall(self)


def parse_plan(raw: str) -> FaultPlan:
    """THE one parser for user-supplied plans — inline JSON or a JSON
    file path prefixed ``@`` (shared by ``$ZNICZ_FAULT_PLAN``,
    ``serve --fault-plan`` and ``chaos --plan``)."""
    if raw.startswith("@"):
        with open(raw[1:]) as fh:
            raw = fh.read()
    return FaultPlan.from_dict(json.loads(raw))


_active: FaultPlan | None = None
_env_checked = False
_install_lock = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    """Make ``plan`` the process-wide active plan (replacing any)."""
    global _active, _env_checked
    with _install_lock:
        _active, _env_checked = plan, True
    return plan


def uninstall(plan: FaultPlan | None = None) -> None:
    """Deactivate injection (optionally only if ``plan`` is active —
    so a context manager never tears down a newer plan)."""
    global _active
    with _install_lock:
        if plan is None or _active is plan:
            _active = None


def active() -> FaultPlan | None:
    """The current plan; resolves ``$ZNICZ_FAULT_PLAN`` on first call so
    subprocess workers (elastic fleets, the serve CLI) pick plans up
    with zero wiring."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        with _install_lock:
            if _active is None and not _env_checked:
                _env_checked = True
                try:
                    _active = FaultPlan.from_env()
                except Exception as e:          # a broken plan must not
                    import logging              # take the process down
                    logging.getLogger(__name__).warning(
                        "ignoring unparseable ZNICZ_FAULT_PLAN: %s", e)
    return _active


def inject(site: str) -> None:
    """The one call instrumented code makes — no-op without a plan."""
    plan = active()
    if plan is not None:
        plan.fire(site)
