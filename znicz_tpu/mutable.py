"""Mutable boolean gates for unit-graph control flow.

Capability parity with the reference's ``veles/mutable.py`` (mount empty —
surveyed contract, SURVEY.md §2.1): ``Bool`` objects shared by reference
between units act as gates (``gate_block``, ``gate_skip``); they support
assignment-through (``<<=``), logical composition (``&``, ``|``, ``~``) that
stays *live* (re-evaluated at read time), and on-change callbacks used by
Decision to trigger snapshots.

These gates live in host Python between jitted steps — they are deliberately
NOT traced (SURVEY.md §7 hard-part (b): phase control-flow stays in Python;
the compute inside a phase is one fused jitted function)."""

from __future__ import annotations

from typing import Callable


class Bool:
    """A shared, watchable boolean cell."""

    def __init__(self, value: bool = False):
        self._value = bool(value)
        self._watchers: list[Callable[[Bool], None]] = []

    @property
    def value(self) -> bool:
        return self._value

    def set(self, value) -> "Bool":
        value = bool(value)
        if value != self._value:
            self._value = value
            for w in list(self._watchers):
                w(self)
        return self

    def __ilshift__(self, value):  # b <<= True  (reference assignment idiom)
        return self.set(value)

    def on_change(self, fn: Callable[["Bool"], None]) -> None:
        self._watchers.append(fn)

    def __bool__(self) -> bool:
        return self._value

    # live logical composition -------------------------------------------
    def __invert__(self) -> "DerivedBool":
        return DerivedBool(lambda: not bool(self), (self,))

    def __and__(self, other) -> "DerivedBool":
        return DerivedBool(lambda: bool(self) and bool(other),
                           (self, other))

    def __or__(self, other) -> "DerivedBool":
        return DerivedBool(lambda: bool(self) or bool(other), (self, other))

    def __repr__(self):
        return f"Bool({bool(self)})"


class DerivedBool(Bool):
    """Live view over other Bools; recomputed at every read."""

    def __init__(self, expr: Callable[[], bool], sources: tuple = ()):
        super().__init__(False)
        self._expr = expr
        self._sources = sources
        self._last = self._expr()
        for s in sources:
            if isinstance(s, Bool):   # plain or derived: chains propagate
                s.on_change(lambda _s: self._notify())

    def _notify(self):
        value = self._expr()
        if value == self._last:       # edge-triggered like plain Bool
            return
        self._last = value
        for w in list(self._watchers):
            w(self)

    @property
    def value(self) -> bool:
        return self._expr()

    def set(self, value):
        raise TypeError("DerivedBool is read-only")

    def __ilshift__(self, value):
        raise TypeError("DerivedBool is read-only")

    def __bool__(self) -> bool:
        return self._expr()
