"""MnistRBMWorkflow: greedy stacked-RBM pretraining + MLP fine-tune.

Parity target: the reference's RBM pretraining recipe (SURVEY.md §2.2
RBM row — ``rbm_units`` existed to pretrain sigmoid MLPs layer-by-layer
before backprop, the classic Hinton deep-belief-net workflow the
reference's MnistRBM sample exercised).

TPU-first: each RBM in the stack trains through
``parallel.rbm.FusedRBMTrainer`` (whole CD-1 epochs as one device-side
scan), hidden probabilities feed the next level, and the resulting
(W, hbias) pairs initialize an ``all2all_sigmoid`` MLP fine-tuned by the
ordinary ``StandardWorkflow`` gradient chain — pretraining and
fine-tuning share Vectors, so the hand-off is a plain array install.

Run: ``python -m znicz_tpu.models.mnist_rbm [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import zlib

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)
from .mnist import MnistLoader

root.mnist_rbm.setdefaults({
    "minibatch_size": 100,
    "hidden": [256, 64],            # stacked RBM sizes (784→256→64)
    # CD needs enough epochs to learn real features — an undertrained
    # RBM hands the MLP a smaller-than-random init and slows it down,
    # and an overcooked lr collapses hidden biases (dead features)
    "pretrain": {"epochs": 10, "learning_rate": 0.1, "momentum": 0.5,
                 "weights_decay": 2e-4},
    "layers": None,                 # derived from `hidden` when None
    "decision": {"max_epochs": 6, "fail_iterations": 20},
    "synthetic": {"n_train": 5000, "n_valid": 1000, "n_test": 1000,
                  "noise": 0.35},
})


def _mlp_layers(hidden) -> list:
    # sigmoid derivative tops out at 0.25 per layer (vs tanh's 1.0), so
    # the working lr is well above the tanh sample's 0.03
    layers = [{"type": "all2all_sigmoid",
               "->": {"output_sample_shape": h},
               "<-": {"learning_rate": 0.5, "gradient_moment": 0.9}}
              for h in hidden]
    layers.append({"type": "softmax", "->": {"output_sample_shape": 10},
                   "<-": {"learning_rate": 0.5,
                          "gradient_moment": 0.9}})
    return layers


def pretrain_stack(data: np.ndarray, hidden, *, epochs=3,
                   learning_rate=0.1, momentum=0.5, weights_decay=2e-4,
                   batch=100) -> list:
    """Greedy layer-wise CD-1 pretraining; returns [(W, hbias), …].

    ``data`` rows are visible probabilities in [0, 1]-ish range; each
    level trains on the previous level's hidden probabilities (the
    mean-field stacking recipe)."""
    from ..ops import rbm as rbm_ops
    from ..parallel.rbm import FusedRBMTrainer
    import jax.numpy as jnp

    gen = prng.get("rbm")
    v = np.asarray(data, np.float32).reshape(len(data), -1)
    # binary RBMs model visible PROBABILITIES: the loader's normalized
    # data (linear → [-1, 1]) must be min-max scaled into [0, 1] or CD's
    # (v0 − v1) statistics drift the weights into sigmoid saturation.
    # The affine map is folded back into the returned level-0 weights
    # below, so the installed layer reproduces the pretrained hidden
    # probabilities on the UNSCALED inputs the fine-tune MLP serves.
    lo, hi = v.min(), v.max()
    a, b = 1.0 / ((hi - lo) or 1.0), -lo / ((hi - lo) or 1.0)
    v = a * v + b
    out = []
    for level, n_hidden in enumerate(hidden):
        n_visible = v.shape[1]
        w0 = gen.normal(0.0, 0.01, (n_visible, n_hidden))
        tr = FusedRBMTrainer(
            w0, np.zeros(n_visible, np.float32),
            np.zeros(n_hidden, np.float32),
            seed=gen.stream_seed,
            unit_id=zlib.crc32(f"rbm_pre{level}".encode()),
            learning_rate=learning_rate, momentum=momentum,
            weights_decay=weights_decay)
        dev = jnp.asarray(v)
        idx = np.arange(len(v))
        for epoch in range(epochs):
            tr.train_epoch(dev, idx, batch, epoch)
        w, _, hb = (np.asarray(p) for p in tr.params)
        if level == 0:
            # fold the [0,1] rescale into the layer: σ((a·x+b)·W + c) ==
            # σ(x·(a·W) + (c + b·ΣᵢWᵢ)) — exact, so the fine-tune MLP
            # reproduces the pretrained hidden probs on raw inputs
            hb = hb + b * w.sum(axis=0)
            w = a * w
        out.append((w, hb))
        # next level trains on this level's hidden probabilities
        v = np.asarray(rbm_ops.hidden_probs(jnp.asarray(v),
                                            tr.params[0], tr.params[2],
                                            jnp), np.float32)
    return out


class MnistRBMWorkflow(StandardWorkflow):
    """Sigmoid MLP whose hidden layers are RBM-pretrainable."""

    def __init__(self, workflow=None, name="MnistRBMWorkflow",
                 layers=None, decision_config=None,
                 snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        loader = MnistLoader(
            minibatch_size=root.mnist_rbm.get("minibatch_size", 100),
            synthetic_sizes=kwargs.get("synthetic_sizes")
            or root.mnist_rbm.synthetic.to_dict())
        super().__init__(
            None, name,
            layers=layers or root.mnist_rbm.get("layers")
            or _mlp_layers(root.mnist_rbm.get("hidden", [256, 64])),
            loader=loader,
            loss_function="softmax",
            decision_config=decision_config
            or root.mnist_rbm.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.mnist_rbm, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)

    def install_pretrained(self, stack) -> None:
        """Copy pretrained (W, hbias) pairs into the hidden layers'
        Vectors (requires ``initialize()`` first)."""
        for unit, (w, hb) in zip(self.forwards, stack):
            if unit.weights.mem.shape != w.shape:
                raise ValueError(
                    f"{unit.name}: pretrained {w.shape} vs layer "
                    f"{unit.weights.mem.shape}")
            unit.weights.mem = np.asarray(w, np.float32)
            unit.bias.mem = np.asarray(hb, np.float32)


def run(device: Device | None = None, epochs: int | None = None,
        pretrain: bool = True, fused: bool = False,
        **kwargs) -> MnistRBMWorkflow:
    """Pretrain the stack (optional), install, fine-tune; returns the
    finished workflow."""
    wf = MnistRBMWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    if pretrain:
        cfg = root.mnist_rbm.pretrain.to_dict()
        # pretrain on the TRAIN split only — original_data is laid out
        # [test | valid | train], and CD must not see evaluation rows
        n_eval = sum(wf.loader.class_lengths[:2])
        stack = pretrain_stack(
            np.asarray(wf.loader.original_data.mem[n_eval:]),
            root.mnist_rbm.get("hidden", [256, 64]),
            epochs=cfg.get("epochs", 3),
            learning_rate=cfg.get("learning_rate", 0.1),
            momentum=cfg.get("momentum", 0.5),
            weights_decay=cfg.get("weights_decay", 2e-4),
            batch=wf.loader.max_minibatch_size)
        wf.install_pretrained(stack)
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--no-pretrain", action="store_true")
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs,
             pretrain=not args.no_pretrain)
    for m in wf.decision.epoch_metrics[-3:]:
        print(m)


if __name__ == "__main__":
    main()
