"""KohonenWorkflow: the reference's self-organizing-map sample.

Parity target: the reference Kohonen sample (SURVEY.md §2.2 Samples row /
§3.5 call stack / BASELINE.json config 5): loader → KohonenForward
(winner-take-all) → KohonenTrainer (neighborhood pull) → KohonenDecision
(weight-change stop) in a minibatch loop — no gradient chain.

Data: 2-D points from a seeded mixture of gaussian clusters (the classic
SOM demo distribution); after training the 2-D neuron sheet unfolds over
the clusters and quantization error drops.

Run: ``python -m znicz_tpu.models.kohonen [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..loader.fullbatch import FullBatchLoader
from ..logger import MetricsWriter
from ..accelerated_units import AcceleratedWorkflow
from ..nn.kohonen import (KohonenDecision, KohonenForward, KohonenTrainer,
                          make_train_only_gate)
from ..ops import kohonen as som_ops

root.kohonen.setdefaults({
    "minibatch_size": 100,
    "shape": (8, 8),
    "learning_rate": 0.5,
    "decision": {"max_epochs": 30, "epsilon": 1e-4},
    "synthetic": {"n_train": 2000, "n_clusters": 5, "noise": 0.08},
})


class SOMLoader(FullBatchLoader):
    """Seeded 2-D gaussian-cluster mixture; train set only."""

    def load_data(self) -> None:
        cfg = root.kohonen.synthetic.to_dict()
        gen = prng.get("kohonen_synthetic")
        k, n = cfg["n_clusters"], cfg["n_train"]
        centers = gen.uniform(-1.0, 1.0, (k, 2))
        which = gen.randint(0, k, n)
        pts = centers[which] + gen.normal(0.0, cfg["noise"], (n, 2))
        self.original_data.mem = pts.astype(np.float32)
        self.original_labels.mem = which.astype(np.int32)
        self.class_lengths = [0, 0, n]


class KohonenWorkflow(AcceleratedWorkflow):
    """BASELINE config 5: the SOM minibatch loop."""

    def __init__(self, workflow=None, name="KohonenWorkflow", shape=None,
                 decision_config=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.metrics_writer = MetricsWriter()
        shape = shape or root.kohonen.shape
        self.loader = SOMLoader(
            self, minibatch_size=root.kohonen.get("minibatch_size", 100))
        self.add_unit(self.loader)
        self.loader.link_from(self.start_point)
        self.forward = KohonenForward(self, name="kohonen_forward",
                                      shape=shape)
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"))
        self.forward.link_from(self.loader)
        self.trainer = KohonenTrainer(
            self, name="kohonen_trainer",
            learning_rate=root.kohonen.get("learning_rate", 0.5))
        self.trainer.setup_from_forward(self.forward)
        self.trainer.link_from(self.forward)
        cfg = decision_config or root.kohonen.decision.to_dict()
        self.decision = KohonenDecision(self, name="decision", **cfg)
        self.decision.link_loader(self.loader)
        self.decision.link_trainer(self.trainer)
        self.decision.link_from(self.trainer)
        self.trainer.gate_skip = make_train_only_gate(self.loader,
                                                      self.decision)
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete
        self.loader.link_from(self.decision)   # minibatch loop back-edge

    def quantization_error(self) -> float:
        x = self.loader.original_data.mem
        return float(som_ops.quantization_error(
            x.reshape(len(x), -1), self.forward.weights.mem, np))

    # -- fused TPU hot path ------------------------------------------------
    def run_fused(self, max_epochs: int | None = None):
        """Whole epochs as one jitted scan (parallel.som); Decision's
        stop logic stays host-side between epochs."""
        from ..parallel.som import FusedSOMTrainer

        assert self.initialized, "initialize() first"
        ms = root.common.get("mesh_shape")
        if isinstance(ms, str):
            from ..parallel.mesh import parse_mesh_arg
            try:
                ms = parse_mesh_arg(ms)
            except ValueError:
                ms = None
        if ms is not None and tuple(ms) != (1, 1):
            # the SOM scan has no mesh path: a CLI --mesh must not be
            # silently ignored (bench.py restamps its rows the same
            # way for this config)
            self.warning("the kohonen SOM fused path has no mesh "
                         "support; --mesh is ignored and training "
                         "runs single-device")
        tr = FusedSOMTrainer(np.asarray(self.forward.weights.mem),
                             self.forward.shape, workflow=self)
        from ..loader.base import TRAIN

        loader, decision = self.loader, self.decision
        data = loader.original_data.devmem
        epochs = max_epochs or decision.max_epochs or 30
        batch = loader.max_minibatch_size
        first = True
        for epoch in range(loader.epoch_number, epochs):
            loader.epoch_number = epoch
            if not first:   # initialize() already built epoch 0's plan —
                loader._build_epoch_plan()   # same shuffle stream as the
            first = False                    # unit-graph loop
            lr, sigma = self.trainer.schedules()
            perm = loader._shuffled[TRAIN]
            diff = tr.train_epoch(data, perm, batch, lr, sigma)
            decision.epoch_metrics.append(
                {"epoch": epoch, "weights_diff": diff})
            self.metrics_writer.write(kind="epoch", epoch=epoch,
                                      weights_diff=diff)
            if diff < decision.epsilon:
                break
        decision.complete.set(True)
        tr.write_back(self.forward)
        return tr


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> KohonenWorkflow:
    wf = KohonenWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    if fused:
        wf.run_fused()
    else:
        wf.run()
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--fused", action="store_true")
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs,
             fused=args.fused)
    for m in wf.decision.epoch_metrics[-5:]:
        print(m)
    print("quantization error:", wf.quantization_error())


if __name__ == "__main__":
    main()
