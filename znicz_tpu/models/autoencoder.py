"""MnistAEWorkflow: the reference's MNIST convolutional autoencoder.

Parity target: the reference ``mnist_ae`` sample (SURVEY.md §2.2 Samples
row "MNIST autoencoder (Conv/Deconv)" / BASELINE.json config 4): a
Conv + Pooling encoder mirrored by a Depooling + Deconv decoder, trained
with MSE against the input image — exercising ``Deconv``/``GDDeconv``/
``Depooling`` (SURVEY.md §7 build-plan stage 7).

Topology (via ``StandardWorkflow`` layers config; ``tie`` back-references
give the decoder its encoder pairing): conv 5×5×16 pad 2 → maxpool 2×2 →
depooling(tie=pool) → deconv 5×5 (16→1) pad 2, loss = MSE(input).

Run: ``python -m znicz_tpu.models.autoencoder [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import numpy as np

from ..backends import Device
from ..config import root
from ..loader.fullbatch import FullBatchLoaderMSE
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)
from .mnist import MnistLoader

root.mnist_ae.setdefaults({
    "minibatch_size": 100,
    "layers": [
        # conv-MSE gradients sum over all 28×28 output positions, so the
        # stable lr is ~2 orders below the classifier samples'
        {"type": "conv", "->": {"n_kernels": 16, "kx": 5, "ky": 5,
                                "padding": 2},
         "<-": {"learning_rate": 0.0002, "gradient_moment": 0.9}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "depooling", "->": {"tie": 1}},
        {"type": "deconv", "->": {"n_kernels": 16, "kx": 5, "ky": 5,
                                  "padding": 2, "n_channels": 1},
         "<-": {"learning_rate": 0.0002, "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 10, "fail_iterations": 50},
    "synthetic": {"n_train": 2000, "n_valid": 400, "n_test": 400,
                  "noise": 0.35},
})


class MnistAELoader(FullBatchLoaderMSE, MnistLoader):
    """MNIST images as NHWC (28, 28, 1) with target = input (the
    FullBatchLoaderMSE autoencoder default)."""

    def load_data(self) -> None:
        MnistLoader.load_data(self)
        self.original_data.mem = self.original_data.mem.reshape(
            -1, 28, 28, 1).astype(np.float32)


class MnistAEWorkflow(StandardWorkflow):
    """BASELINE config 4: Conv/Pool encoder + Depool/Deconv decoder, MSE."""

    def __init__(self, workflow=None, name="MnistAEWorkflow", layers=None,
                 decision_config=None, snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        loader = MnistAELoader(
            minibatch_size=root.mnist_ae.get("minibatch_size", 100),
            synthetic_sizes=kwargs.get("synthetic_sizes")
            or root.mnist_ae.synthetic.to_dict())
        super().__init__(
            None, name,
            layers=layers or root.mnist_ae.get("layers")
            or root.mnist_ae.layers,
            loader=loader,
            loss_function="mse",
            decision_config=decision_config
            or root.mnist_ae.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.mnist_ae, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> MnistAEWorkflow:
    """Build, initialize and train; ``fused=True`` (the CLI's --fused)
    takes the compiled whole-step path instead of the unit-graph tick
    loop.  Returns the finished workflow."""
    wf = MnistAEWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs)
    for m in wf.decision.epoch_metrics:
        print(m)
    print("time table:", wf.time_table()[:6])


if __name__ == "__main__":
    main()
