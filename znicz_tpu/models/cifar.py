"""CifarWorkflow: the reference's CIFAR-10 conv sample.

Parity target: the reference CIFAR sample (SURVEY.md §2.2 Samples row /
BASELINE.json config 2): a Conv+Pooling+LRN+FC stack trained with the
GDConv/GDPooling chain via ``StandardWorkflow``.

Topology (reference-style caffe-era CIFAR net, declared via the
``layers=[...]`` config): conv 5×5×32 → maxpool 2 → LRN → conv 5×5×32 →
avgpool 2 → all2all_tanh 64 → softmax 10.

Data: real CIFAR-10 python batches are used when present (searched under
``root.common.cifar_dir``); otherwise a deterministic synthetic stand-in
(class prototypes + noise over 32×32×3, seeded) — this environment has no
network and the tests only need a learnable, reproducible problem.

Run: ``python -m znicz_tpu.models.cifar [--backend=numpy|xla] [--epochs=N]``
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..loader.fullbatch import FullBatchLoader
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)

root.cifar.setdefaults({
    "minibatch_size": 100,
    "layers": [
        {"type": "conv_tanh",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": 2},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "norm", "->": {"n": 5, "alpha": 1e-4, "beta": 0.75}},
        {"type": "conv_tanh",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": 2},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
        {"type": "avg_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 64},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 10, "fail_iterations": 50},
    "synthetic": {"n_train": 2000, "n_valid": 400, "n_test": 400,
                  "noise": 0.3, "size": 32},
})


def _find_cifar() -> str | None:
    for cand in (root.common.get("cifar_dir"), "/root/data/cifar10",
                 os.path.expanduser("~/.cache/cifar10")):
        if cand and os.path.exists(os.path.join(cand, "data_batch_1")):
            return cand
    return None


class CifarLoader(FullBatchLoader):
    """Real CIFAR-10 when available, deterministic synthetic otherwise.

    Samples are NHWC float32 (H=W=32, C=3) — the TPU-native layout
    (channels on the lane dim); the reference stored flat row-major."""

    def __init__(self, workflow=None, name=None, synthetic_sizes=None,
                 **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super().__init__(workflow, name or "cifar_loader", **kwargs)
        self.synthetic_sizes = synthetic_sizes

    def load_data(self) -> None:
        cifar_dir = _find_cifar()
        if cifar_dir:
            self._load_real(cifar_dir)
        else:
            self._load_synthetic()

    def _load_real(self, d: str) -> None:
        def batch(fname):
            with open(os.path.join(d, fname), "rb") as fh:
                raw = pickle.load(fh, encoding="bytes")
            x = raw[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return x.astype(np.float32), np.asarray(raw[b"labels"],
                                                    np.int32)
        train = [batch(f"data_batch_{i}") for i in range(1, 6)]
        te_x, te_y = batch("test_batch")
        tr_x = np.concatenate([b[0] for b in train])
        tr_y = np.concatenate([b[1] for b in train])
        n_valid = 5000
        self.original_data.mem = np.concatenate(
            [te_x, tr_x[:n_valid], tr_x[n_valid:]])
        self.original_labels.mem = np.concatenate(
            [te_y, tr_y[:n_valid], tr_y[n_valid:]])
        self.class_lengths = [len(te_x), n_valid, len(tr_x) - n_valid]

    def _load_synthetic(self) -> None:
        cfg = self.synthetic_sizes or root.cifar.synthetic.to_dict()
        n_test, n_valid, n_train = (cfg["n_test"], cfg["n_valid"],
                                    cfg["n_train"])
        noise, size = cfg.get("noise", 0.3), cfg.get("size", 32)
        gen = prng.get("cifar_synthetic")
        protos = gen.normal(0.0, 1.0, (10, size, size, 3))
        n = n_test + n_valid + n_train
        labels = gen.randint(0, 10, n).astype(np.int32)
        data = (protos[labels]
                + gen.normal(0.0, noise, (n, size, size, 3))).astype(
                    np.float32)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]


class CifarWorkflow(StandardWorkflow):
    """BASELINE config 2: Conv+Pool+LRN+FC + GDConv/GDPooling chain."""

    def __init__(self, workflow=None, name="CifarWorkflow", layers=None,
                 decision_config=None, snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        loader = CifarLoader(
            minibatch_size=root.cifar.get("minibatch_size", 100),
            **{k: v for k, v in kwargs.items()
               if k in ("synthetic_sizes",)})
        super().__init__(
            None, name,
            layers=layers or root.cifar.get("layers") or root.cifar.layers,
            loader=loader,
            loss_function="softmax",
            decision_config=decision_config
            or root.cifar.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.cifar, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> CifarWorkflow:
    """Build, initialize and train; ``fused=True`` (the CLI's --fused)
    takes the compiled whole-step path instead of the unit-graph tick
    loop.  Returns the finished workflow."""
    wf = CifarWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs)
    for m in wf.decision.epoch_metrics:
        print(m)
    print("time table:", wf.time_table()[:6])


if __name__ == "__main__":
    main()
