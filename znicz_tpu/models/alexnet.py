"""AlexNetWorkflow: the reference's ImageNet AlexNet sample.

Parity target: the reference ``samples/AlexNet`` (SURVEY.md §2.2 Samples
row [baseline: samples/AlexNet] / BASELINE.json config 3 and the headline
metric "ImageNet AlexNet images/sec/chip").  Classic 2012 geometry over
227×227×3 NHWC inputs: conv11/4·96 → LRN → pool3/2 → conv5·256(pad 2) →
LRN → pool3/2 → conv3·384 → conv3·384 → conv3·256 → pool3/2 → dropout →
fc4096 → dropout → fc4096 → softmax(1000), strict-ReLU activations
(SURVEY.md §2.2 ConvStrictRELU), LRN normalization [baseline], dropout
[baseline: AlexNet config].

Data: ImageNet is not available in this environment (no network —
SURVEY.md caveat); a seeded synthetic stand-in with the real tensor
geometry serves training/benchmarking.  Shapes and class count are
configurable so tests can shrink the net (``root.alexnet``).

Run: ``python -m znicz_tpu.models.alexnet [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..loader.fullbatch import FullBatchLoader
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)


def make_layers(n_classes: int = 1000, lr: float = 0.01,
                moment: float = 0.9, wd: float = 5e-4,
                widths=(96, 256, 384, 384, 256, 4096, 4096)) -> list:
    """The AlexNet ``layers`` config; ``widths`` shrinks the net for
    tests."""
    gd = {"learning_rate": lr, "gradient_moment": moment,
          "weights_decay": wd}
    c1, c2, c3, c4, c5, f6, f7 = widths
    return [
        {"type": "conv_str",
         "->": {"n_kernels": c1, "kx": 11, "ky": 11, "sliding": 4},
         "<-": dict(gd)},
        {"type": "norm", "->": {"n": 5, "alpha": 1e-4, "beta": 0.75,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": 2}},
        {"type": "conv_str",
         "->": {"n_kernels": c2, "kx": 5, "ky": 5, "padding": 2},
         "<-": dict(gd)},
        {"type": "norm", "->": {"n": 5, "alpha": 1e-4, "beta": 0.75,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": 2}},
        {"type": "conv_str",
         "->": {"n_kernels": c3, "kx": 3, "ky": 3, "padding": 1},
         "<-": dict(gd)},
        {"type": "conv_str",
         "->": {"n_kernels": c4, "kx": 3, "ky": 3, "padding": 1},
         "<-": dict(gd)},
        {"type": "conv_str",
         "->": {"n_kernels": c5, "kx": 3, "ky": 3, "padding": 1},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": 2}},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "all2all_str", "->": {"output_sample_shape": f6},
         "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "all2all_str", "->": {"output_sample_shape": f7},
         "<-": dict(gd)},
        {"type": "softmax", "->": {"output_sample_shape": n_classes},
         "<-": dict(gd)},
    ]


root.alexnet.setdefaults({
    "minibatch_size": 128,
    "size": 227,
    "n_classes": 1000,
    "layers": None,   # default: make_layers(n_classes)
    "decision": {"max_epochs": 10, "fail_iterations": 50},
    "synthetic": {"n_train": 512, "n_valid": 128, "n_test": 128,
                  "noise": 0.4},
    #: directory-per-class tree with train/ (and optionally valid/,
    #: test/) subtrees → the reference's on-the-fly ImageNet pipeline:
    #: decode at decode_size, random-crop to size + mirror at train
    #: time, center crop at eval (loader.augment.RandomCropFlip)
    "data_dir": None,
    "decode_size": 256,
})


class ImagenetSyntheticLoader(FullBatchLoader):
    """Seeded synthetic stand-in with ImageNet tensor geometry: per-class
    prototypes + noise at (size, size, 3) NHWC."""

    def __init__(self, workflow=None, name=None, size=227, n_classes=1000,
                 synthetic_sizes=None, **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super().__init__(workflow, name or "imagenet_loader", **kwargs)
        self.size = int(size)
        self.n_classes = int(n_classes)
        self.synthetic_sizes = synthetic_sizes

    def load_data(self) -> None:
        cfg = self.synthetic_sizes or root.alexnet.synthetic.to_dict()
        n_test, n_valid, n_train = (cfg["n_test"], cfg["n_valid"],
                                    cfg["n_train"])
        noise, s = cfg.get("noise", 0.4), self.size
        gen = prng.get("imagenet_synthetic")
        n = n_test + n_valid + n_train
        labels = gen.randint(0, self.n_classes, n).astype(np.int32)
        # low-res per-class prototypes upsampled per sample keep the
        # synthetic set learnable without storing n_classes full images —
        # upsampling inside the loop avoids a ~646 MB full prototype
        # sheet at the default (1000, 227) config; float32 throughout
        protos = gen.normal(0.0, 1.0, (self.n_classes, 8, 8, 3)).astype(
            np.float32)
        rep = s // 8 + 1
        data = np.empty((n, s, s, 3), np.float32)
        for i in range(n):
            up = protos[labels[i]].repeat(rep, axis=0).repeat(rep, axis=1)
            data[i] = up[:s, :s, :] + gen.normal(
                0.0, noise, (s, s, 3)).astype(np.float32)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]


def make_imagenet_loader(data_dir: str, size: int = 227,
                         decode_size: int = 256,
                         minibatch_size: int = 128):
    """The reference's on-the-fly ImageNet pipeline, TPU-edition: disk
    tree bigger than HBM, host decode at (decode_size)² in a thread
    pool, counter-RNG random (size)² crop + mirror at train time, all
    overlapped with device compute by the double-buffered prefetcher
    (SURVEY.md §2.2 "Znicz loaders" row, imagenet pipeline)."""
    import os

    from ..loader.augment import RandomCropFlip
    from ..loader.streaming import OnTheFlyImageLoader
    splits = {}
    for split, key in (("train", "train_paths"),
                       ("valid", "validation_paths"),
                       ("test", "test_paths")):
        p = os.path.join(data_dir, split)
        if os.path.isdir(p):
            splits[key] = [p]
    if "train_paths" not in splits:
        raise ValueError(f"{data_dir}: no train/ subtree")
    return OnTheFlyImageLoader(
        size=(decode_size, decode_size),
        augment=RandomCropFlip((size, size)),
        minibatch_size=minibatch_size, **splits)


class AlexNetWorkflow(StandardWorkflow):
    """BASELINE config 3: the ImageNet AlexNet training workflow."""

    def __init__(self, workflow=None, name="AlexNetWorkflow", layers=None,
                 decision_config=None, snapshotter_config=None,
                 lr_adjuster_config=None,
                 data_dir=None, **kwargs):
        data_dir = data_dir or root.alexnet.get("data_dir")
        if data_dir:
            loader = make_imagenet_loader(
                data_dir,
                size=root.alexnet.get("size", 227),
                decode_size=root.alexnet.get("decode_size", 256),
                minibatch_size=root.alexnet.get("minibatch_size", 128))
        else:
            loader = ImagenetSyntheticLoader(
                minibatch_size=root.alexnet.get("minibatch_size", 128),
                size=root.alexnet.get("size", 227),
                n_classes=root.alexnet.get("n_classes", 1000),
                synthetic_sizes=kwargs.get("synthetic_sizes"))
        super().__init__(
            None, name,
            layers=layers or root.alexnet.get("layers")
            or make_layers(root.alexnet.get("n_classes", 1000)),
            loader=loader,
            loss_function="softmax",
            decision_config=decision_config
            or root.alexnet.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.alexnet, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = True, mesh=None, **kwargs) -> AlexNetWorkflow:
    """Build, initialize and train.  ``fused=True`` (default) uses the
    compiled whole-step path — the per-unit tick loop at this scale only
    serves as the correctness cross-check."""
    wf = AlexNetWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, mesh=mesh, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--unit-graph", action="store_true",
                        help="per-unit tick loop instead of the fused step")
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs,
             fused=not args.unit_graph)
    for m in wf.decision.epoch_metrics[-3:]:
        print(m)


if __name__ == "__main__":
    main()
