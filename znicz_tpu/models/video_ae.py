"""VideoAEWorkflow: the reference's video_ae sample.

Parity target: the reference ``samples/video_ae`` (SURVEY.md §2.2
Samples row "… video_ae …"): a convolutional autoencoder over video
FRAMES — the reference treated a video as a frame pool and learned a
per-frame compressed representation (no temporal model; the 2015-era
stack has no recurrence).

Data: deterministic synthetic "video" — sequences of a moving/breathing
blob with per-sequence texture, sliced into frames; frames from the
same sequence stay in the same split so validation measures
generalization to unseen sequences, not unseen frames of a seen one.

Run: ``python -m znicz_tpu.models.video_ae [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..loader.fullbatch import FullBatchLoaderMSE
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)

root.video_ae.setdefaults({
    "minibatch_size": 50,
    "frame": 16,                    # square frame edge (pixels)
    "layers": [
        {"type": "conv", "->": {"n_kernels": 12, "kx": 5, "ky": 5,
                                "padding": 2},
         "<-": {"learning_rate": 5e-4, "gradient_moment": 0.9}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "depooling", "->": {"tie": 1}},
        {"type": "deconv", "->": {"tie": 0},
         "<-": {"learning_rate": 5e-4, "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 10, "fail_iterations": 30},
    "synthetic": {"n_train_seq": 24, "n_valid_seq": 6, "n_test_seq": 0,
                  "frames_per_seq": 12},
})


def synth_sequence(gen, frames: int, size: int) -> np.ndarray:
    """One synthetic clip: a gaussian blob orbiting with per-sequence
    radius/speed/texture → (frames, size, size, 1) float32 in [0, 1]."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    cx = cy = (size - 1) / 2.0
    radius = gen.uniform(size * 0.15, size * 0.3)
    speed = gen.uniform(0.2, 0.6)
    phase = gen.uniform(0, 2 * np.pi)
    sigma = gen.uniform(1.2, 2.5)
    texture = gen.uniform(0.0, 0.15, (size, size))
    out = np.empty((frames, size, size, 1), np.float32)
    for f in range(frames):
        a = phase + speed * f
        by = cy + radius * np.sin(a)
        bx = cx + radius * np.cos(a)
        blob = np.exp(-((yy - by) ** 2 + (xx - bx) ** 2)
                      / (2.0 * sigma * sigma))
        out[f, :, :, 0] = np.clip(blob + texture, 0.0, 1.0)
    return out


class VideoFrameLoader(FullBatchLoaderMSE):
    """Synthetic clips sliced into frames; splits are per-SEQUENCE."""

    def __init__(self, workflow=None, name=None, synthetic_sizes=None,
                 **kwargs):
        super().__init__(workflow, name or "video_loader", **kwargs)
        self.synthetic_sizes = synthetic_sizes

    def load_data(self) -> None:
        cfg = self.synthetic_sizes or root.video_ae.synthetic.to_dict()
        size = root.video_ae.get("frame", 16)
        fps = cfg["frames_per_seq"]
        gen = prng.get("video_ae")
        chunks, lengths = [], []
        for n_seq in (cfg["n_test_seq"], cfg["n_valid_seq"],
                      cfg["n_train_seq"]):
            frames = [synth_sequence(gen, fps, size)
                      for _ in range(n_seq)]
            chunks.append(np.concatenate(frames) if frames
                          else np.empty((0, size, size, 1), np.float32))
            lengths.append(n_seq * fps)
        self.original_data.mem = np.concatenate(chunks)
        self.original_labels.mem = np.zeros(sum(lengths), np.int32)
        self.class_lengths = lengths


class VideoAEWorkflow(StandardWorkflow):
    """Conv/pool encoder + tied depool/deconv decoder over frames."""

    def __init__(self, workflow=None, name="VideoAEWorkflow",
                 layers=None, decision_config=None,
                 snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        loader = VideoFrameLoader(
            minibatch_size=root.video_ae.get("minibatch_size", 50),
            synthetic_sizes=kwargs.get("synthetic_sizes")
            or root.video_ae.synthetic.to_dict())
        super().__init__(
            None, name,
            layers=layers or root.video_ae.get("layers"),
            loader=loader,
            loss_function="mse",
            decision_config=decision_config
            or root.video_ae.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.video_ae, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> VideoAEWorkflow:
    """Build, initialize and train; ``fused=True`` (the CLI's --fused)
    takes the compiled whole-step path.  Returns the workflow."""
    wf = VideoAEWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--fused", action="store_true")
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs,
             fused=args.fused)
    for m in wf.decision.epoch_metrics[-3:]:
        print(m)


if __name__ == "__main__":
    main()
