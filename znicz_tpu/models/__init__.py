"""Runnable sample models (reference: veles/znicz/samples — SURVEY.md §2.2):
MNIST MLP, CIFAR-10 conv, AlexNet, MNIST autoencoder, Kohonen SOM,
Wine tabular MLP, stacked-RBM DBN pretraining, kanji glyph streaming,
video frame autoencoder, YaleFaces identity-under-lighting."""
