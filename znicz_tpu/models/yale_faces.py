"""YaleFacesWorkflow: the reference's YaleFaces sample.

Parity target: the reference ``samples/YaleFaces`` (SURVEY.md §2.2
Samples row "plus Wine, kanji, video_ae, YaleFaces …"): identifying
subjects from grayscale face images under strongly varying
illumination (the Extended Yale B premise).  No face data exists in
this environment (SURVEY.md caveat), so — like the kanji sample — the
dataset is procedural: each subject is a deterministic facial geometry
(head ellipse, eye/brow/nose/mouth layout), and every sample renders
that geometry under a random *directional light* plus noise, keeping
the dataset's defining nuisance axis.

TPU-first detail: trains from DISK through ``OnTheFlyImageLoader``
with crop-only ``RandomCropFlip`` augmentation (mirror disabled —
identity classification; crops decouple position from identity), i.e.
the second sample-level consumer of the streaming loader family and
the first of the augmentation stage.

Run: ``python -m znicz_tpu.models.yale_faces [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import os

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)

root.yale_faces.setdefaults({
    "minibatch_size": 40,
    "n_subjects": 10,
    "per_subject": {"train": 24, "valid": 8},
    "render_size": 38,              # decoded frame (square, grayscale)
    "size": 32,                     # post-crop input fed to the net
    "layers": None,                 # default: make_layers()
    "decision": {"max_epochs": 10, "fail_iterations": 30},
})


def make_layers(n_subjects: int = 10, lr: float = 0.05,
                moment: float = 0.9) -> list:
    gd = {"learning_rate": lr, "gradient_moment": moment}
    return [
        {"type": "conv_tanh", "->": {"n_kernels": 8, "kx": 5, "ky": 5,
                                     "padding": 2}, "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "conv_tanh", "->": {"n_kernels": 16, "kx": 3, "ky": 3,
                                     "padding": 1}, "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 64},
         "<-": dict(gd)},
        {"type": "softmax", "->": {"output_sample_shape": n_subjects},
         "<-": dict(gd)},
    ]


def subject_geometries(n_subjects: int, stream="yale_subjects"):
    """Deterministic per-subject facial geometry — the 'identity'."""
    gen = prng.get(stream)
    subjects = []
    for _ in range(n_subjects):
        subjects.append({
            "head": (0.50 + gen.uniform(-0.04, 0.04),       # cy
                     0.50 + gen.uniform(-0.03, 0.03),       # cx
                     0.42 + gen.uniform(-0.06, 0.06),       # ry
                     0.30 + gen.uniform(-0.06, 0.06)),      # rx
            "eye_y": 0.38 + gen.uniform(-0.05, 0.05),
            "eye_dx": 0.13 + gen.uniform(-0.04, 0.04),
            "eye_r": 0.035 + gen.uniform(0.0, 0.03),
            "brow_dy": 0.07 + gen.uniform(0.0, 0.04),
            "nose_len": 0.16 + gen.uniform(-0.05, 0.08),
            "mouth_y": 0.72 + gen.uniform(-0.05, 0.05),
            "mouth_w": 0.16 + gen.uniform(-0.05, 0.08),
            "mouth_curve": gen.uniform(-0.06, 0.06),
        })
    return subjects


def render_face(geom: dict, size: int, angle: float, gen) -> np.ndarray:
    """One sample: the subject's geometry shaded by a directional light
    from ``angle`` (the Yale B illumination axis) + sensor noise →
    uint8 grayscale.  Pure numpy rasterization — no font/draw deps."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / (size - 1)
    cy, cx, ry, rx = geom["head"]
    face = ((yy - cy) / ry) ** 2 + ((xx - cx) / rx) ** 2 <= 1.0
    img = np.where(face, 0.75, 0.05).astype(np.float32)

    def dark_disc(y, x, r, depth):
        m = (yy - y) ** 2 + (xx - x) ** 2 <= r * r
        img[m] = depth

    for sx in (-1.0, 1.0):
        ex = cx + sx * geom["eye_dx"]
        dark_disc(geom["eye_y"], ex, geom["eye_r"], 0.15)       # eye
        brow = (np.abs(yy - (geom["eye_y"] - geom["brow_dy"])) < 0.018) \
            & (np.abs(xx - ex) < geom["eye_r"] + 0.03)
        img[brow & face] = 0.25                                  # brow
    nose = (np.abs(xx - cx) < 0.015) \
        & (yy > geom["eye_y"]) & (yy < geom["eye_y"] + geom["nose_len"])
    img[nose & face] = 0.45
    mouth = (np.abs(yy - (geom["mouth_y"]
                          + geom["mouth_curve"]
                          * ((xx - cx) / max(geom["mouth_w"], 1e-3)) ** 2)
                    ) < 0.02) & (np.abs(xx - cx) < geom["mouth_w"])
    img[mouth & face] = 0.2
    # directional illumination: light from `angle`, hard falloff on the
    # far side — the dataset's defining nuisance variable
    lx, ly = np.cos(angle), np.sin(angle)
    shade = 0.25 + 0.75 * np.clip(
        0.5 + 1.2 * (lx * (xx - cx) + ly * (yy - cy)), 0.0, 1.0)
    img = img * shade
    img = np.clip(img + gen.normal(0.0, 0.03, img.shape), 0.0, 1.0)
    return (img * 255).astype(np.uint8)


def render_dataset(directory: str, n_subjects: int, per_subject: dict,
                   size: int) -> dict:
    """Render the face tree (``train/subj_XX/*.png``, ``valid/...``);
    idempotent via a geometry marker (same contract as the kanji
    renderer)."""
    import json
    import shutil

    from PIL import Image

    splits = {k: os.path.join(directory, k) for k in per_subject}
    marker = os.path.join(directory, ".complete")
    want = json.dumps({"n_subjects": n_subjects, "size": size,
                       "per_subject": dict(sorted(per_subject.items()))},
                      sort_keys=True)
    if os.path.exists(marker):
        with open(marker) as fh:
            if fh.read().strip() == want:
                return splits
    # stale OR partial tree (interrupted render leaves no marker):
    # always start clean — leftover frames of another geometry would
    # mix into the directory scan
    shutil.rmtree(directory, ignore_errors=True)
    subjects = subject_geometries(n_subjects)
    gen = prng.get("yale_render")
    for split, n_per in per_subject.items():
        for si, geom in enumerate(subjects):
            d = os.path.join(splits[split], f"subj_{si:02d}")
            os.makedirs(d, exist_ok=True)
            for i in range(n_per):
                angle = float(gen.uniform(0.0, 2.0 * np.pi))
                Image.fromarray(render_face(geom, size, angle, gen)).save(
                    os.path.join(d, f"im{i:03d}.png"))
    with open(marker, "w") as fh:
        fh.write(want + "\n")
    return splits


class YaleFacesWorkflow(StandardWorkflow):
    """Conv identity classifier over the rendered face tree, served by
    the streaming loader with crop-only augmentation."""

    def __init__(self, workflow=None, name="YaleFacesWorkflow",
                 layers=None, data_dir: str | None = None,
                 decision_config=None, snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        from ..loader.augment import RandomCropFlip
        from ..loader.streaming import OnTheFlyImageLoader

        cfg = root.yale_faces
        n_subj = cfg.get("n_subjects", 10)
        data_dir = data_dir or os.path.join(
            root.common.get("cache_dir", ".cache"), "yale_faces")
        splits = render_dataset(data_dir, n_subj,
                                cfg.per_subject.to_dict(),
                                cfg.get("render_size", 38))
        size = cfg.get("size", 32)
        loader = OnTheFlyImageLoader(
            None, "yale_loader",
            train_paths=[splits["train"]],
            validation_paths=[splits["valid"]],
            grayscale=True,
            augment=RandomCropFlip((size, size), mirror=False),
            minibatch_size=cfg.get("minibatch_size", 40))
        super().__init__(
            None, name,
            layers=layers or cfg.get("layers") or make_layers(n_subj),
            loader=loader,
            loss_function="softmax",
            decision_config=decision_config or cfg.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.yale_faces, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> YaleFacesWorkflow:
    wf = YaleFacesWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--fused", action="store_true")
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs,
             fused=args.fused)
    for m in wf.decision.epoch_metrics[-3:]:
        print(m)


if __name__ == "__main__":
    main()
