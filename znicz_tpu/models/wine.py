"""WineWorkflow: the reference's Wine tabular-classification sample.

Parity target: the reference ``samples/Wine`` (mount empty — surveyed
contract, SURVEY.md §2.2 Samples row "plus Wine, kanji, …"): the
smallest end-to-end demo — the UCI Wine dataset (178 samples, 13
chemical features, 3 cultivars) through a tiny MLP.  Historically the
reference's "hello world" workflow.

TPU-first: same StandardWorkflow assembly as every other sample; the
loader reads the classic ``wine.data`` CSV when present and falls back
to a deterministic synthetic stand-in with the real dataset's geometry
(13 features, 3 classes) otherwise — this environment ships no
datasets (BASELINE.md provenance note).

Run: ``python -m znicz_tpu.models.wine [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import os

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..loader.fullbatch import FullBatchLoader
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)

root.wine.setdefaults({
    "minibatch_size": 30,
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 3},
         "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 40, "fail_iterations": 20},
    "synthetic": {"n_train": 118, "n_valid": 30, "n_test": 30,
                  "noise": 0.5},
})


def _find_wine_csv() -> str | None:
    for cand in (root.common.get("wine_path"), "/root/data/wine.data",
                 os.path.expanduser("~/.cache/wine.data")):
        if cand and os.path.exists(cand):
            return cand
    return None


class WineLoader(FullBatchLoader):
    """UCI wine.data CSV (label first, 13 features) when available,
    deterministic synthetic stand-in with the same geometry otherwise."""

    FEATURES, CLASSES = 13, 3

    def __init__(self, workflow=None, name=None, synthetic_sizes=None,
                 **kwargs):
        # features span wildly different scales (proline ~1000s,
        # hue ~1) — the mean/dispersion normalizer is essential
        kwargs.setdefault("normalization_type", "mean_disp")
        super().__init__(workflow, name or "wine_loader", **kwargs)
        self.synthetic_sizes = synthetic_sizes

    def load_data(self) -> None:
        path = _find_wine_csv()
        if path:
            self._load_real(path)
        else:
            self._load_synthetic()

    def _load_real(self, path: str) -> None:
        raw = np.loadtxt(path, delimiter=",", dtype=np.float32)
        labels = raw[:, 0].astype(np.int32) - 1       # 1..3 → 0..2
        data = raw[:, 1:]
        # deterministic shuffle, then [test | valid | train] split
        order = prng.get("wine_split").permutation(len(raw))
        data, labels = data[order], labels[order]
        n = len(raw)
        n_test = n_valid = max(1, n // 6)
        self.original_data.mem = np.ascontiguousarray(data)
        self.original_labels.mem = np.ascontiguousarray(labels)
        self.class_lengths = [n_test, n_valid, n - n_test - n_valid]

    def _load_synthetic(self) -> None:
        cfg = self.synthetic_sizes or root.wine.synthetic.to_dict()
        n_test, n_valid, n_train = (cfg["n_test"], cfg["n_valid"],
                                    cfg["n_train"])
        noise = cfg.get("noise", 0.5)
        gen = prng.get("wine_synthetic")
        protos = gen.normal(0.0, 1.0, (self.CLASSES, self.FEATURES))
        n = n_test + n_valid + n_train
        labels = gen.randint(0, self.CLASSES, n).astype(np.int32)
        data = (protos[labels] + gen.normal(0.0, noise,
                                            (n, self.FEATURES)))
        # mimic the real dataset's heterogeneous feature scales so the
        # normalizer path is actually exercised
        scales = 10.0 ** gen.uniform(-1.0, 3.0, (1, self.FEATURES))
        self.original_data.mem = (data * scales).astype(np.float32)
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]


class WineWorkflow(StandardWorkflow):
    """Reference samples/Wine: 13-feature MLP, tanh hidden, softmax."""

    def __init__(self, workflow=None, name="WineWorkflow", layers=None,
                 decision_config=None, snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        loader = WineLoader(
            minibatch_size=root.wine.get("minibatch_size", 30),
            **{k: v for k, v in kwargs.items()
               if k in ("synthetic_sizes",)})
        super().__init__(
            None, name,
            layers=layers or root.wine.get("layers"),
            loader=loader,
            loss_function="softmax",
            decision_config=decision_config
            or root.wine.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.wine, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> WineWorkflow:
    """Build, initialize and train; ``fused=True`` (the CLI's --fused)
    takes the compiled whole-step path instead of the unit-graph tick
    loop.  Returns the finished workflow."""
    wf = WineWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs)
    for m in wf.decision.epoch_metrics[-3:]:
        print(m)


if __name__ == "__main__":
    main()
