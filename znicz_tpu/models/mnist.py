"""MnistWorkflow: the reference's canonical MNIST MLP sample.

Parity target: the reference MNIST sample (SURVEY.md §2.2 Samples row /
BASELINE.json config 1): ``All2AllTanh(100) → All2AllSoftmax(10)`` with
``GradientDescent`` training via ``StandardWorkflow``.

Data: real MNIST IDX files are used when present (searched in
``root.common.mnist_dir`` and conventional locations); otherwise a
deterministic synthetic MNIST stand-in is generated (class prototypes +
noise, seeded) — this environment has no network, and the convergence
tests only need a learnable, reproducible 10-class 28×28 problem.

Run:  ``python -m znicz_tpu.models.mnist [--backend=numpy|xla] [--epochs=N]``
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..loader.fullbatch import FullBatchLoader
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)

root.mnist.setdefaults({
    "minibatch_size": 100,
    "layers": [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 100},
         "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 10, "fail_iterations": 50},
    "synthetic": {"n_train": 5000, "n_valid": 1000, "n_test": 1000,
                  "noise": 0.35},
})


def _find_mnist_idx() -> str | None:
    for cand in (root.common.get("mnist_dir"), "/root/data/mnist",
                 os.path.expanduser("~/.cache/mnist")):
        if cand and os.path.exists(
                os.path.join(cand, "train-images-idx3-ubyte.gz")):
            return cand
    return None


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fh:
        magic, = struct.unpack(">H", fh.read(4)[2:])
        dims = magic & 0xFF
        # IDX: magic(4) then dims×uint32 sizes
        fh.seek(4)
        shape = struct.unpack(f">{dims}I", fh.read(4 * dims))
        return np.frombuffer(fh.read(), np.uint8).reshape(shape)


class MnistLoader(FullBatchLoader):
    """Real MNIST when available, deterministic synthetic otherwise."""

    def __init__(self, workflow=None, name=None, synthetic_sizes=None,
                 **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super().__init__(workflow, name or "mnist_loader", **kwargs)
        self.synthetic_sizes = synthetic_sizes

    def load_data(self) -> None:
        mnist_dir = _find_mnist_idx()
        if mnist_dir:
            self._load_real(mnist_dir)
        else:
            self._load_synthetic()

    def _load_real(self, d: str) -> None:
        tr_x = _read_idx(os.path.join(d, "train-images-idx3-ubyte.gz"))
        tr_y = _read_idx(os.path.join(d, "train-labels-idx1-ubyte.gz"))
        te_x = _read_idx(os.path.join(d, "t10k-images-idx3-ubyte.gz"))
        te_y = _read_idx(os.path.join(d, "t10k-labels-idx1-ubyte.gz"))
        # 10k held out for validation on the real 60k set; adapt for
        # smaller drop-in datasets (same idx format, fewer rows)
        n_valid = min(10000, len(tr_x) // 6)
        # order: [test | validation | train] to match class indices
        self.original_data.mem = np.concatenate(
            [te_x, tr_x[:n_valid], tr_x[n_valid:]]).astype(
                np.float32).reshape(-1, 784)
        self.original_labels.mem = np.concatenate(
            [te_y, tr_y[:n_valid], tr_y[n_valid:]]).astype(np.int32)
        self.class_lengths = [len(te_x), n_valid, len(tr_x) - n_valid]

    def _load_synthetic(self) -> None:
        cfg = self.synthetic_sizes or root.mnist.synthetic.to_dict()
        n_test, n_valid, n_train = (cfg["n_test"], cfg["n_valid"],
                                    cfg["n_train"])
        noise = cfg.get("noise", 0.35)
        gen = prng.get("mnist_synthetic")
        protos = gen.normal(0.0, 1.0, (10, 784))
        n = n_test + n_valid + n_train
        labels = gen.randint(0, 10, n).astype(np.int32)
        data = (protos[labels]
                + gen.normal(0.0, noise, (n, 784))).astype(np.float32)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]


class MnistWorkflow(StandardWorkflow):
    """BASELINE config 1: All2AllTanh → All2AllSoftmax + GD chain."""

    def __init__(self, workflow=None, name="MnistWorkflow", layers=None,
                 decision_config=None, snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        loader = MnistLoader(
            minibatch_size=root.mnist.get("minibatch_size", 100),
            **{k: v for k, v in kwargs.items()
               if k in ("synthetic_sizes",)})
        super().__init__(
            None, name,
            layers=layers or root.mnist.get("layers")
            or root.mnist.layers,
            loader=loader,
            loss_function="softmax",
            decision_config=decision_config
            or root.mnist.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.mnist, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> MnistWorkflow:
    """Build, initialize and train; ``fused=True`` (the CLI's --fused)
    takes the compiled whole-step path instead of the unit-graph tick
    loop.  Returns the finished workflow."""
    wf = MnistWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs)
    for m in wf.decision.epoch_metrics:
        print(m)
    print("time table:", wf.time_table()[:6])


if __name__ == "__main__":
    main()
