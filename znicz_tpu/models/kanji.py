"""KanjiWorkflow: the reference's kanji sample, streaming edition.

Parity target: the reference ``samples/kanji`` (SURVEY.md §2.2 Samples
row "plus Wine, kanji, …"): classifying rendered character glyphs.  The
upstream sample *generated* its training images (rendering characters
with transforms) rather than shipping a dataset — mirrored here by a
deterministic procedural glyph renderer (per-class stroke skeletons +
per-sample jitter), since this environment has no fonts or datasets.

TPU-first twist: unlike the in-HBM samples, kanji deliberately trains
from DISK through the streaming loader family (``OnTheFlyImageLoader``:
thread-pool PNG decode per minibatch, double-buffered host→HBM
prefetch) — the sample-level consumer of the SURVEY §2.2 "on-the-fly
image loader" row.

Run: ``python -m znicz_tpu.models.kanji [--backend=…] [--epochs=N]``
"""

from __future__ import annotations

import os

import numpy as np

from .. import prng
from ..backends import Device
from ..config import root
from ..standard_workflow import (StandardWorkflow,
                                 sample_snapshotter_config)

root.kanji.setdefaults({
    "minibatch_size": 50,
    "n_classes": 12,
    "per_class": {"train": 40, "valid": 10},
    "size": 24,                     # glyph canvas (pixels, square)
    "layers": [
        {"type": "conv_tanh", "->": {"n_kernels": 12, "kx": 5,
                                     "padding": 2},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 64},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 12},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ],
    "decision": {"max_epochs": 8, "fail_iterations": 30},
})


def render_glyph(cls_strokes, size: int, gen, jitter: float = 1.5
                 ) -> np.ndarray:
    """One sample: the class's stroke skeleton + per-sample endpoint
    jitter, shift, and pixel noise → uint8 grayscale image.  Strokes
    rasterize through PIL's ImageDraw (PIL is already the hard
    dependency of this whole path — the PNGs are saved and decoded
    with it)."""
    from PIL import Image, ImageDraw

    canvas = Image.new("L", (size, size), 0)
    draw = ImageDraw.Draw(canvas)
    sy, sx = gen.uniform(-2.0, 2.0, 2)
    for (p0, p1) in cls_strokes:
        j = gen.uniform(-jitter, jitter, 4)
        draw.line([(p0[1] + sx + j[1], p0[0] + sy + j[0]),
                   (p1[1] + sx + j[3], p1[0] + sy + j[2])],
                  fill=255, width=2)
    img = np.asarray(canvas, np.float32) / 255.0
    img = np.clip(img + gen.uniform(0.0, 0.15, img.shape), 0.0, 1.0)
    return (img * 255).astype(np.uint8)


def class_strokes(n_classes: int, size: int, stream="kanji_glyphs"):
    """Deterministic per-class stroke skeletons (3–6 segments each) —
    the 'font' of this procedural character set."""
    gen = prng.get(stream)
    out = []
    for _ in range(n_classes):
        n_strokes = int(gen.randint(3, 7))
        pts = gen.uniform(2, size - 3, (n_strokes, 4))
        out.append([((p[0], p[1]), (p[2], p[3])) for p in pts])
    return out


def render_dataset(directory: str, n_classes: int, per_class: dict,
                   size: int) -> dict:
    """Render the glyph tree (``train/cls_XX/*.png``, ``valid/...``);
    idempotent — existing trees are reused.  Returns split→path."""
    import json
    import shutil

    from PIL import Image

    splits = {k: os.path.join(directory, k) for k in per_class}
    marker = os.path.join(directory, ".complete")
    # the marker records the rendering geometry: a cached tree is only
    # reused when it matches the requested config (a stale 12-class tree
    # under a widened softmax would otherwise train silently wrong)
    want = json.dumps({"n_classes": n_classes, "size": size,
                       "per_class": dict(sorted(per_class.items()))},
                      sort_keys=True)
    if os.path.exists(marker):
        with open(marker) as fh:
            if fh.read().strip() == want:
                return splits
    # stale OR partial tree (interrupted render leaves no marker):
    # always start clean — leftover glyphs of another config would mix
    # into the directory scan
    shutil.rmtree(directory, ignore_errors=True)
    strokes = class_strokes(n_classes, size)
    gen = prng.get("kanji_render")
    for split, n_per in per_class.items():
        for ci, cls in enumerate(strokes):
            d = os.path.join(splits[split], f"cls_{ci:02d}")
            os.makedirs(d, exist_ok=True)
            for i in range(n_per):
                Image.fromarray(render_glyph(cls, size, gen)).save(
                    os.path.join(d, f"im{i:03d}.png"))
    with open(marker, "w") as fh:
        fh.write(want + "\n")
    return splits


class KanjiWorkflow(StandardWorkflow):
    """Conv classifier over the rendered glyph tree, served by the
    streaming on-the-fly image loader (disk → decode pool → HBM)."""

    def __init__(self, workflow=None, name="KanjiWorkflow", layers=None,
                 data_dir: str | None = None, decision_config=None,
                 snapshotter_config=None,
                 lr_adjuster_config=None, **kwargs):
        from ..loader.streaming import OnTheFlyImageLoader

        cfg = root.kanji
        data_dir = data_dir or os.path.join(
            root.common.get("cache_dir", ".cache"), "kanji_glyphs")
        splits = render_dataset(data_dir, cfg.get("n_classes", 12),
                                cfg.per_class.to_dict(),
                                cfg.get("size", 24))
        loader = OnTheFlyImageLoader(
            None, "kanji_loader",
            train_paths=[splits["train"]],
            validation_paths=[splits["valid"]],
            grayscale=True,
            minibatch_size=cfg.get("minibatch_size", 50))
        super().__init__(
            None, name,
            layers=layers or cfg.get("layers"),
            loader=loader,
            loss_function="softmax",
            decision_config=decision_config or cfg.decision.to_dict(),
            snapshotter_config=sample_snapshotter_config(
                root.kanji, snapshotter_config),
            lr_adjuster_config=lr_adjuster_config)


def run(device: Device | None = None, epochs: int | None = None,
        fused: bool = False, **kwargs) -> KanjiWorkflow:
    """Build, initialize and train; ``fused=True`` streams epochs
    through the prefetching StreamTrainer.  Returns the workflow."""
    wf = KanjiWorkflow(**kwargs)
    if epochs is not None:
        wf.decision.max_epochs = epochs
    wf.initialize(device=device or Device.create("auto"))
    wf.train(fused=fused, max_epochs=epochs)
    return wf


def main(argv: list[str] | None = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--backend", default="auto",
                        choices=("auto", "numpy", "xla"))
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--fused", action="store_true")
    args = parser.parse_args(argv)
    wf = run(device=Device.create(args.backend), epochs=args.epochs,
             fused=args.fused)
    for m in wf.decision.epoch_metrics[-3:]:
        print(m)


if __name__ == "__main__":
    main()
