"""TPU tunnel liveness: the one copy of the relay pre-check logic.

The axon PJRT plugin reaches the chip through a local gRPC relay
(`PALLAS_AXON_POOL_IPS`, `jax.devices()` traffic on :8083).  When the
relay is down the port REFUSES in milliseconds while PJRT's channel
retries forever — so a TCP connect is the cheap liveness signal, and
both `bench.py`'s backend wait and `tools/tpu_probe.py` gate their
heavyweight subprocess probes on it.  The pre-check only applies when
the relay env var is explicitly present: on a host with a
directly-attached TPU (no relay), gating on a port nobody listens on
would block probing forever.
"""

from __future__ import annotations

import os
import socket


def relay_endpoint() -> tuple[str, int] | None:
    """(ip, port) of the relay, or None when no relay is configured
    (direct-attached TPU — skip the pre-check entirely)."""
    ips = os.environ.get("PALLAS_AXON_POOL_IPS")
    if not ips:
        return None
    return (ips.split(",")[0],
            int(os.environ.get("TPU_PROBE_RELAY_PORT", 8083)))


def relay_ok(timeout: float = 2.0) -> bool:
    """True when probing is worth attempting: either no relay is
    configured (direct TPU), or the relay port accepts."""
    ep = relay_endpoint()
    if ep is None:
        return True
    try:
        with socket.create_connection(ep, timeout):
            return True
    except OSError:
        return False
