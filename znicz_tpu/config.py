"""Attribute-tree global configuration.

Capability parity with the reference's config system (upstream layout
``veles/config.py``; the /root/reference mount was empty during the survey —
see SURVEY.md caveat — so this is built to the surveyed contract, not to
file:line citations): a process-global ``root`` attribute tree; config files
are plain Python executed against ``root`` (``root.mnist.update({...})``);
any dotted path can be read/written/overridden from the CLI.

TPU-first notes: config values feed *static* arguments of jitted train steps
(shapes, layer specs, hyperparameters), so the tree converts cleanly to
hashable tuples via :meth:`Config.to_dict`.
"""

from __future__ import annotations

import copy
import json


_MISSING = object()


class Config:
    """A node in the attribute tree.

    Accessing an unknown attribute creates an empty child node, so config
    files can write ``root.a.b.c = 1`` without pre-declaring anything.
    """

    def __init__(self, path: str = "root", **kwargs):
        self.__dict__["_path"] = path
        for k, v in kwargs.items():
            setattr(self, k, v)

    # -- tree behaviour ----------------------------------------------------
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        child = Config(f"{self._path}.{name}")
        self.__dict__[name] = child
        return child

    def __setattr__(self, name: str, value):
        if isinstance(value, dict):
            node = self.__dict__.get(name)
            if not isinstance(node, Config):
                node = Config(f"{self._path}.{name}")
                self.__dict__[name] = node
            node.update(value)
        else:
            self.__dict__[name] = value

    def update(self, values: dict) -> "Config":
        """Recursively merge a dict into this node (reference ``update`` UX)."""
        for k, v in values.items():
            if isinstance(v, dict):
                node = self.__dict__.get(k)
                if not isinstance(node, Config):
                    node = Config(f"{self._path}.{k}")
                    self.__dict__[k] = node
                node.update(v)
            else:
                self.__dict__[k] = v
        return self

    def setdefaults(self, values: dict) -> "Config":
        """Recursively fill only MISSING keys (module-level sample
        defaults): a config file executed before the module import — the
        launcher's two-file order — keeps its values."""
        for k, v in values.items():
            existing = self.__dict__.get(k, _MISSING)
            if isinstance(v, dict):
                node = existing
                if not isinstance(node, Config):
                    if existing is not _MISSING:
                        continue   # leaf already set by the user
                    node = Config(f"{self._path}.{k}")
                    self.__dict__[k] = node
                node.setdefaults(v)
            elif existing is _MISSING:
                self.__dict__[k] = v
        return self

    # -- access helpers ----------------------------------------------------
    @staticmethod
    def _descend(node, part):
        """One path step: Config attribute, list index, or dict key —
        paths may continue into container leaves (``layers.0.<-.lr``),
        which the genetics module needs to evolve per-layer hypers."""
        if isinstance(node, Config):
            return node.__dict__.get(part, _MISSING)
        if isinstance(node, list):
            try:
                return node[int(part)]
            except (ValueError, IndexError):
                return _MISSING
        if isinstance(node, dict):
            return node.get(part, _MISSING)
        return _MISSING

    def get(self, name: str, default=None):
        """Read a leaf without creating intermediate nodes."""
        node = self
        for part in name.split("."):
            node = self._descend(node, part)
            if node is _MISSING:
                return default
        return default if isinstance(node, Config) and not node.to_dict() \
            else node

    def set_path(self, dotted: str, value):
        """CLI-style override: ``set_path("mnist.lr", 0.01)``; paths may
        index into list/dict leaves (``mnist.layers.0.<-.learning_rate``)."""
        parts = dotted.split(".")
        node = self
        for part in parts[:-1]:
            if isinstance(node, Config):
                node = getattr(node, part)
            elif isinstance(node, list):
                node = node[int(part)]
            else:
                node = node[part]
        last = parts[-1]
        if isinstance(node, Config):
            setattr(node, last, value)
        elif isinstance(node, list):
            node[int(last)] = value
        else:
            node[last] = value

    def to_dict(self) -> dict:
        out = {}
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            out[k] = v.to_dict() if isinstance(v, Config) else v
        return out

    def clone(self) -> "Config":
        c = Config(self._path)
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            c.__dict__[k] = v.clone() if isinstance(v, Config) \
                else copy.deepcopy(v)
        return c

    def __contains__(self, name: str) -> bool:
        return name in self.__dict__ and not name.startswith("_")

    def __repr__(self):
        return f"Config({self._path}: {json.dumps(self.to_dict(), default=str)})"


#: Process-global configuration tree (reference: global ``root``).
root = Config("root")
root.common.update({
    "precision_type": "float32",
    # mixed-precision knobs consumed by StandardWorkflow.train():
    # compute_dtype = MXU operand dtype, storage_dtype = inter-layer
    # activation dtype.  None → the fused path's float32 defaults,
    # keeping fused vs unit-graph numerics identical; set "bfloat16"
    # (config file or --set) to opt in.
    "compute_dtype": None,
    "storage_dtype": None,
    "engine": {"backend": "auto"},  # auto | numpy | xla
    "seed": 1234,
    "snapshot_dir": "snapshots",
    "cache_dir": ".cache",
})


def apply_overrides(overrides: list[str], tree: Config = root) -> None:
    """Apply CLI ``path=value`` overrides; values parsed as Python literals."""
    import ast

    for item in overrides:
        path, _, raw = item.partition("=")
        try:
            value = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            value = raw
        tree.set_path(path.strip(), value)
