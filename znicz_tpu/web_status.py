"""Web status page: live training progress over HTTP.

Parity target: the reference ``veles/web_status.py`` + the live-plot
graphics server/client pair (mount empty — surveyed contract, SURVEY.md
§2.1 Web status + Plotting rows: master HTTP page with progress; a
separate process rendering live error curves from a zmq plot stream).

TPU-first: a stdlib ``http.server`` thread serving ``/status.json``
(workflow name, epoch, metrics history, per-unit time table, device),
``/plot.svg`` (live error/loss curves rendered server-side — the
graphics-*client* process becomes the viewer's browser; no zmq, no
pickled matplotlib state), and a self-refreshing HTML page at ``/`` —
no tornado/twisted; multi-host SPMD replaces the slave roster with the
JAX process/device inventory."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .telemetry.registry import PROMETHEUS_CONTENT_TYPE, REGISTRY

_PAGE = """<!doctype html><html><head><title>znicz-tpu status</title>
<meta http-equiv="refresh" content="3"><style>
body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:2px 8px;text-align:right}
th{background:#eee}</style></head><body>
<h2 id="t">znicz-tpu</h2><img src="plot.svg" alt=""><div id="s">loading…
</div>
<script>
fetch('status.json').then(r=>r.json()).then(d=>{
 document.getElementById('t').textContent=d.workflow+' — epoch '+d.epoch;
 let h='<p>device: '+d.device+' | units: '+d.n_units+'</p>';
 if(d.metrics.length){
  h+='<table><tr>'+Object.keys(d.metrics[0]).map(k=>'<th>'+k+'</th>')
    .join('')+'</tr>';
  for(const m of d.metrics.slice(-12))
   h+='<tr>'+Object.values(m).map(v=>'<td>'+(typeof v==='number'?
     v.toPrecision(5):v)+'</td>').join('')+'</tr>';
  h+='</table>';}
 document.getElementById('s').innerHTML=h;});
</script></body></html>"""

#: metric-name suffixes plotted (one polyline each), with fixed colors.
_PLOT_KEYS = (("train_err_pct", "#c33"), ("validation_err_pct", "#36c"),
              ("test_err_pct", "#393"), ("train_loss", "#c93"),
              ("validation_loss", "#66c"), ("train_mse", "#c3c"))


def render_plot_svg(metrics: list, width=640, height=240) -> str:
    """Live error/loss curves as a standalone SVG (the reference's
    AccumulatingPlotter error-curve view, rendered server-side with no
    matplotlib/zmq dependency).

    Each series is normalized to its own [min, max] — percentages
    (0–100) and losses (~0–2) stay readable on one canvas; the legend
    carries each curve's own range.  Non-finite points (a diverged
    loss going NaN is exactly when someone opens this page) are
    dropped per-series instead of poisoning the scale."""
    import math
    pad = 34
    series = []        # (key, color, [(epoch index, value), ...])
    for k, c in _PLOT_KEYS:
        pts = [(i, float(m[k])) for i, m in enumerate(metrics)
               if k in m and math.isfinite(float(m[k]))]
        if len(pts) >= 2:
            series.append((k, c, pts))
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
             f'height="{height}" style="background:#fff;font:10px '
             f'monospace">']
    if not series:
        parts.append(f'<text x="{width // 2}" y="{height // 2}" '
                     f'text-anchor="middle">waiting for ≥2 finite '
                     f'epochs…</text></svg>')
        return "".join(parts)
    n = max(i for _, _, pts in series for i, _ in pts) + 1

    def sx(i):
        return pad + i * (width - 2 * pad) / max(n - 1, 1)

    parts.append(f'<rect x="{pad}" y="{pad - 10}" '
                 f'width="{width - 2 * pad}" '
                 f'height="{height - 2 * pad + 10}" fill="none" '
                 f'stroke="#ccc"/>')
    for pos, (k, color, pts_kv) in enumerate(series):
        vals = [v for _, v in pts_kv]
        lo, hi = min(vals), max(vals)
        span = (hi - lo) or 1.0

        def sy(val, lo=lo, span=span):
            return height - pad - (val - lo) * (height - 2 * pad) / span

        # x keeps the epoch index, so curves stay epoch-aligned even
        # when a series has non-finite gaps
        pts = " ".join(f"{sx(i):.1f},{sy(val):.1f}"
                       for i, val in pts_kv)
        parts.append(f'<polyline points="{pts}" fill="none" '
                     f'stroke="{color}" stroke-width="1.5"/>')
        parts.append(f'<text x="{pad + 4 + 210 * (pos % 3)}" '
                     f'y="{12 + 11 * (pos // 3)}" fill="{color}">'
                     f'{k} [{lo:.3g}…{hi:.3g}]</text>')
    parts.append("</svg>")
    return "".join(parts)


class StatusServer:
    """Background HTTP server over a live workflow (read-only)."""

    def __init__(self, workflow, host: str = "127.0.0.1", port: int = 0):
        self.workflow = workflow
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):   # keep training logs clean
                pass

            def do_GET(self):
                if self.path.endswith("status.json"):
                    body = json.dumps(outer.snapshot(),
                                      default=float).encode()
                    ctype = "application/json"
                elif self.path.endswith("metrics"):
                    # the training process speaks the same scrape
                    # format as the serving front (telemetry registry:
                    # train_step_time_ms, examples/sec, retry/fault
                    # counters, span histograms)
                    body = REGISTRY.render_prometheus().encode()
                    ctype = PROMETHEUS_CONTENT_TYPE
                elif self.path.endswith("plot.svg"):
                    body = render_plot_svg(
                        outer.snapshot()["metrics"]).encode()
                    ctype = "image/svg+xml"
                else:
                    body = _PAGE.encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def snapshot(self) -> dict:
        wf = self.workflow
        loader = getattr(wf, "loader", None)
        decision = getattr(wf, "decision", None)
        device = getattr(wf, "device", None)
        return {
            "workflow": wf.name,
            "epoch": getattr(loader, "epoch_number", None),
            "complete": bool(getattr(decision, "complete", False)),
            "metrics": list(getattr(decision, "epoch_metrics", []))[-50:],
            "n_units": len(wf.units),
            "device": type(device).__name__ if device else None,
            "time_table": wf.time_table()[:10],
            # the shared registry (step timing/throughput gauges,
            # retry/fault/breaker counters, span histograms) replaces
            # any per-server private metric dict — one store, every
            # view (PR 3 telemetry seam)
            "telemetry": REGISTRY.as_dict(),
        }

    def start(self) -> "StatusServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    @property
    def url(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}/"
