"""Web status page: live training progress over HTTP.

Parity target: the reference ``veles/web_status.py`` (mount empty —
surveyed contract, SURVEY.md §2.1 Web status row: master HTTP page with
progress and connected slaves).

TPU-first: a stdlib ``http.server`` thread serving ``/status.json``
(workflow name, epoch, metrics history, per-unit time table, device) and
a self-refreshing minimal HTML page at ``/`` — no tornado/twisted, no
separate graphics process; multi-host SPMD replaces the slave roster
with the JAX process/device inventory."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!doctype html><html><head><title>znicz-tpu status</title>
<meta http-equiv="refresh" content="3"><style>
body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:2px 8px;text-align:right}
th{background:#eee}</style></head><body>
<h2 id="t">znicz-tpu</h2><div id="s">loading…</div>
<script>
fetch('status.json').then(r=>r.json()).then(d=>{
 document.getElementById('t').textContent=d.workflow+' — epoch '+d.epoch;
 let h='<p>device: '+d.device+' | units: '+d.n_units+'</p>';
 if(d.metrics.length){
  h+='<table><tr>'+Object.keys(d.metrics[0]).map(k=>'<th>'+k+'</th>')
    .join('')+'</tr>';
  for(const m of d.metrics.slice(-12))
   h+='<tr>'+Object.values(m).map(v=>'<td>'+(typeof v==='number'?
     v.toPrecision(5):v)+'</td>').join('')+'</tr>';
  h+='</table>';}
 document.getElementById('s').innerHTML=h;});
</script></body></html>"""


class StatusServer:
    """Background HTTP server over a live workflow (read-only)."""

    def __init__(self, workflow, host: str = "127.0.0.1", port: int = 0):
        self.workflow = workflow
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):   # keep training logs clean
                pass

            def do_GET(self):
                if self.path.endswith("status.json"):
                    body = json.dumps(outer.snapshot(),
                                      default=float).encode()
                    ctype = "application/json"
                else:
                    body = _PAGE.encode()
                    ctype = "text/html"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def snapshot(self) -> dict:
        wf = self.workflow
        loader = getattr(wf, "loader", None)
        decision = getattr(wf, "decision", None)
        device = getattr(wf, "device", None)
        return {
            "workflow": wf.name,
            "epoch": getattr(loader, "epoch_number", None),
            "complete": bool(getattr(decision, "complete", False)),
            "metrics": list(getattr(decision, "epoch_metrics", []))[-50:],
            "n_units": len(wf.units),
            "device": type(device).__name__ if device else None,
            "time_table": wf.time_table()[:10],
        }

    def start(self) -> "StatusServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    @property
    def url(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}/"
