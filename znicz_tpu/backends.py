"""Device abstraction.

Capability parity with the reference's ``veles/backends.py`` (mount empty —
surveyed contract, SURVEY.md §2.1): a ``Device`` family units dispatch on.
The reference had NumpyDevice / OpenCLDevice / CUDADevice plus a device-info
database of tuned BLOCK_SIZEs.  TPU-first redesign:

* ``NumpyDevice`` — the golden, always-available host path (kept 1:1).
* ``XLADevice``  — JAX/XLA path; wraps the PJRT-visible device set (TPU on
  hardware, CPU in tests).  There is no kernel build/queue management to
  expose: XLA owns compilation and scheduling; what the reference's
  device-info DB did (pick BLOCK_SIZE per device/dtype/op) lives in
  ``znicz_tpu.ops.tuning`` for Pallas kernels.
* Backend selection: ``Device.create("auto"|"numpy"|"xla")`` mirrors the
  reference's CLI backend flag.
"""

from __future__ import annotations

import jax
import numpy as np

from .logger import Logger


class Device(Logger):
    """Base device; knows how to move arrays and run compute."""

    backend_name = "abstract"

    #: True when compute runs through JAX/XLA (accelerated path).
    is_xla = False

    @staticmethod
    def create(backend: str = "auto") -> "Device":
        if backend == "auto":
            backend = "xla"
        if backend == "numpy":
            return NumpyDevice()
        if backend in ("xla", "tpu", "jax"):
            return XLADevice()
        raise ValueError(f"unknown backend {backend!r}")

    def put(self, array):
        raise NotImplementedError

    def get(self, array) -> np.ndarray:
        raise NotImplementedError

    def synchronize(self) -> None:
        pass


class NumpyDevice(Device):
    """Host numpy execution — the reference's golden path, kept as such."""

    backend_name = "numpy"
    is_xla = False

    def put(self, array):
        return np.asarray(array)

    def get(self, array) -> np.ndarray:
        return np.asarray(array)


class XLADevice(Device):
    """JAX/XLA execution (TPU on hardware; CPU backend in CI).

    Replaces the reference's OpenCLDevice/CUDADevice + opencl4py/cuda4py
    bindings: device discovery, memory, compilation and queues are all PJRT's
    job; this class only pins a default device and moves host arrays.
    """

    backend_name = "xla"
    is_xla = True

    def __init__(self, device: "jax.Device | None" = None):
        self.jax_device = device or jax.devices()[0]
        self.platform = self.jax_device.platform

    @property
    def is_tpu(self) -> bool:
        return self.platform not in ("cpu", "gpu")

    def put(self, array):
        return jax.device_put(array, self.jax_device)

    def get(self, array) -> np.ndarray:
        return np.asarray(jax.device_get(array))

    def synchronize(self) -> None:
        jax.block_until_ready(
            jax.device_put(np.zeros((), np.float32), self.jax_device))
