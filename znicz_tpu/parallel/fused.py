"""Fused train step: the whole unit chain as one jitted function.

This is the TPU-native execution model (SURVEY.md §7): the unit graph built
by ``StandardWorkflow`` stays the assembly/testing surface, while this
module compiles the SAME math — forward chain + evaluator + hand-written
backward chain + SGD update — into one ``jit``-ted, mesh-shardable step,
eliminating the per-minibatch Python dispatch the reference paid
(SURVEY.md §3.1 hot-loop note).  A whole epoch runs as a ``lax.scan`` over
a precomputed index matrix with the dataset HBM-resident, so the host
touches the device once per epoch, not once per unit per minibatch.

Gradient aggregation across the ``data`` mesh axis is the all-reduce XLA
inserts automatically for the sharded batch dim — the TPU replacement for
the reference's ``apply_data_from_slave`` fold [baseline]."""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import activations, softmax as softmax_ops
from . import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class LayerSpec:
    kind: str                     # "fc" (conv variants arrive with §7.4)
    activation: str               # activations.BY_NAME key; last fc layer
    include_bias: bool            # of a softmax model keeps "linear"
    hypers: tuple                 # (lr, weights_decay, l1_vs_l2, momentum)
    hypers_bias: tuple


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    layers: tuple[LayerSpec, ...]
    loss: str                     # "softmax" | "mse"
    compute_dtype: str = "float32"

    def __post_init__(self):
        for layer in self.layers:
            act = activations.BY_NAME[layer.activation]
            if act.needs_input:
                # forward() caches post-activation values only, so
                # derivative-needs-input activations can't run fused;
                # use the unit-graph path for those.
                raise NotImplementedError(
                    f"activation {layer.activation!r} needs its input "
                    f"for the backward pass and is not supported by the "
                    f"fused step")

    def act(self, i: int):
        return activations.BY_NAME[self.layers[i].activation]


def extract_model(workflow) -> tuple[ModelSpec, list, list]:
    """Read (spec, params, velocities) out of an initialized
    StandardWorkflow.  params/velocities: list of (w, b) numpy pairs."""
    layers, params, vels = [], [], []
    for fwd, gdu in zip(workflow.forwards, workflow.gds):
        from ..nn.all2all import All2All, All2AllSoftmax
        if not isinstance(fwd, All2All):
            raise NotImplementedError(
                f"fused path supports FC layers for now, got {type(fwd)}")
        act = ("linear" if isinstance(fwd, All2AllSoftmax)
               else fwd.ACTIVATION.name)
        layers.append(LayerSpec(
            kind="fc", activation=act, include_bias=fwd.include_bias,
            hypers=(gdu.learning_rate, gdu.weights_decay, gdu.l1_vs_l2,
                    gdu.gradient_moment),
            hypers_bias=(gdu.learning_rate_bias, gdu.weights_decay_bias,
                         gdu.l1_vs_l2_bias, gdu.gradient_moment_bias)))
        params.append((np.asarray(fwd.weights.mem),
                       np.asarray(fwd.bias.mem) if fwd.include_bias
                       else None))
        vels.append((np.asarray(gdu.velocity_weights.mem),
                     np.asarray(gdu.velocity_bias.mem)
                     if fwd.include_bias else None))
    loss = workflow.loss_function
    return ModelSpec(tuple(layers), loss), params, vels


# -- pure math (all traced; spec is static) --------------------------------
def forward(spec: ModelSpec, params, x, *, want_caches: bool):
    """Returns (net_output_pre_loss, caches).  For softmax loss the last
    layer's output is the *logits* (loss fusion happens in the step)."""
    cdt = jnp.dtype(spec.compute_dtype)
    h = x.reshape(x.shape[0], -1)
    caches = [h]
    n = len(spec.layers)
    for i, (layer, (w, b)) in enumerate(zip(spec.layers, params)):
        pre = jnp.dot(h.astype(cdt), w.astype(cdt),
                      preferred_element_type=jnp.float32)
        if b is not None:
            pre = pre + b
        is_last = i == n - 1
        if is_last and spec.loss == "softmax":
            h = pre                       # logits; softmax fused with CE
        else:
            h = spec.act(i).fwd(pre, jnp)
        if want_caches and not is_last:
            caches.append(h)
    return h, caches


def predict(spec: ModelSpec, params, x):
    out, _ = forward(spec, params, x, want_caches=False)
    if spec.loss == "softmax":
        return jax.nn.softmax(out, axis=1)
    return out


def _loss_and_err(spec: ModelSpec, out, target, mask):
    """(mean loss, err w.r.t. last pre-activation, n_err); ``mask`` is a
    per-row 0/1 vector zeroing the wrap-padded tail of a short final
    minibatch, so fused metrics/gradients match the unit-graph exactly."""
    bs = jnp.maximum(jnp.sum(mask), 1.0)
    if spec.loss == "softmax":
        # dispatcher: fused Pallas softmax-CE kernel on TPU, XLA otherwise
        probs, loss, err = softmax_ops.softmax_ce_from_logits(out, target)
        n_err = jnp.sum((jnp.argmax(probs, axis=1) != target) * mask)
        return (jnp.sum(loss * mask) / bs, err * mask[:, None] / bs,
                n_err.astype(jnp.int32))
    diff = (out - target.reshape(out.shape)) * mask[:, None]
    loss = jnp.sum(diff * diff) / (bs * out.shape[1])
    # err w.r.t. the activated output, scaled 1/batch (matches
    # EvaluatorMSE); train_minibatch folds it through the last activation
    return loss, diff / bs, jnp.zeros((), jnp.int32)


def backward(spec: ModelSpec, params, caches, err_y):
    """Hand-written gradient chain (same math as the GD* units)."""
    cdt = jnp.dtype(spec.compute_dtype)
    grads = [None] * len(spec.layers)
    for i in reversed(range(len(spec.layers))):
        w, b = params[i]
        x_i = caches[i]
        gw = jnp.dot(x_i.astype(cdt).T, err_y.astype(cdt),
                     preferred_element_type=jnp.float32)
        gb = jnp.sum(err_y, axis=0) if b is not None else None
        grads[i] = (gw, gb)
        if i > 0:
            err_h = jnp.dot(err_y.astype(cdt), w.astype(cdt).T,
                            preferred_element_type=jnp.float32)
            y_prev = caches[i]
            err_y = spec.act(i - 1).bwd(err_h, y_prev, None, jnp)
    return grads


def apply_updates(spec: ModelSpec, params, vels, grads):
    # Inline update math (not the Pallas update kernel): inside the fused
    # step XLA fuses these elementwise ops into the surrounding graph; the
    # Pallas kernel serves the unit-graph path where each op dispatches
    # separately (the reference's kernel-per-op model).
    new_p, new_v = [], []
    for layer, (w, b), (vw, vb), (gw, gb) in zip(spec.layers, params,
                                                 vels, grads):
        lr, wd, l1, mom = layer.hypers
        reg = wd * ((1.0 - l1) * w + 0.5 * l1 * jnp.sign(w))
        vw2 = mom * vw - lr * (gw + reg)
        w2 = w + vw2
        if b is not None:
            lrb, wdb, l1b, momb = layer.hypers_bias
            regb = wdb * ((1.0 - l1b) * b + 0.5 * l1b * jnp.sign(b))
            vb2 = momb * vb - lrb * (gb + regb)
            b2 = b + vb2
        else:
            b2, vb2 = None, None
        new_p.append((w2, b2))
        new_v.append((vw2, vb2))
    return new_p, new_v


def train_minibatch(spec: ModelSpec, params, vels, x, target, mask=None):
    if mask is None:
        mask = jnp.ones((x.shape[0],), jnp.float32)
    out, caches = forward(spec, params, x, want_caches=True)
    loss, err, n_err = _loss_and_err(spec, out, target, mask)
    if spec.loss == "mse":   # fold through the last layer's activation
        err = spec.act(len(spec.layers) - 1).bwd(err, out, None, jnp)
    grads = backward(spec, params, caches, err)
    params, vels = apply_updates(spec, params, vels, grads)
    metrics = {"loss": loss, "n_err": n_err}
    return params, vels, metrics


def eval_minibatch(spec: ModelSpec, params, x, target, mask=None):
    if mask is None:
        mask = jnp.ones((x.shape[0],), jnp.float32)
    out, _ = forward(spec, params, x, want_caches=False)
    loss, _, n_err = _loss_and_err(spec, out, target, mask)
    return {"loss": loss, "n_err": n_err}


class FusedTrainer:
    """Owns device-resident params and compiled epoch functions.

    ``mesh``: optional ``jax.sharding.Mesh`` with ("data", "model") axes —
    params get TP shardings (mesh.shard_params), batches shard over
    ``data``; XLA inserts the gradient all-reduce.  With no mesh,
    single-device jit."""

    def __init__(self, workflow=None, spec: ModelSpec | None = None,
                 params=None, vels=None, mesh=None):
        if workflow is not None:
            spec, params, vels = extract_model(workflow)
        self.spec = spec
        self.mesh = mesh
        self.workflow = workflow
        if mesh is not None:
            self._param_shardings = [
                (mesh_lib.shard_params(mesh, i, 2),
                 mesh_lib.replicated(mesh))
                for i in range(len(spec.layers))]
            put = lambda a, s: jax.device_put(a, s)      # noqa: E731
            self.params = [
                (put(w, sh[0]), put(b, sh[1]) if b is not None else None)
                for (w, b), sh in zip(params, self._param_shardings)]
            self.vels = [
                (put(vw, sh[0]),
                 put(vb, sh[1]) if vb is not None else None)
                for (vw, vb), sh in zip(vels, self._param_shardings)]
            self._batch_sharding = mesh_lib.shard_batch(mesh)
            self._repl = mesh_lib.replicated(mesh)
        else:
            self.params = jax.device_put(params)
            self.vels = jax.device_put(vels)
            self._batch_sharding = None
        self._train_epoch_fn = None
        self._eval_epoch_fn = None

    # -- epoch-granular compiled drivers ----------------------------------
    def _build(self):
        spec = self.spec

        def train_epoch(params, vels, data, target, idx, mask):
            def body(carry, step):
                params, vels = carry
                step_idx, step_mask = step
                x = jnp.take(data, step_idx, axis=0)
                t = jnp.take(target, step_idx, axis=0)
                if self._batch_sharding is not None:
                    x = jax.lax.with_sharding_constraint(
                        x, self._batch_sharding)
                params, vels, m = train_minibatch(spec, params, vels, x,
                                                  t, step_mask)
                return (params, vels), m
            (params, vels), ms = jax.lax.scan(body, (params, vels),
                                              (idx, mask))
            return params, vels, ms

        def eval_epoch(params, data, target, idx, mask):
            def body(_, step):
                step_idx, step_mask = step
                x = jnp.take(data, step_idx, axis=0)
                t = jnp.take(target, step_idx, axis=0)
                if self._batch_sharding is not None:
                    x = jax.lax.with_sharding_constraint(
                        x, self._batch_sharding)
                return None, eval_minibatch(spec, params, x, t, step_mask)
            _, ms = jax.lax.scan(body, None, (idx, mask))
            return ms

        self._train_epoch_fn = jax.jit(train_epoch, donate_argnums=(0, 1))
        self._eval_epoch_fn = jax.jit(eval_epoch)

    def _idx_matrix(self, indices: np.ndarray,
                    batch: int) -> tuple[np.ndarray, np.ndarray]:
        """(steps, batch) int32 indices + 0/1 mask.  The final short batch
        wraps around for a static shape; the mask zeroes the padded tail
        so metrics and gradients count each sample exactly once."""
        n = len(indices)
        steps = max(1, -(-n // batch))
        padded = np.resize(indices, steps * batch)
        mask = np.zeros(steps * batch, np.float32)
        mask[:n] = 1.0
        return (padded.reshape(steps, batch).astype(np.int32),
                mask.reshape(steps, batch))

    def train_epoch(self, data, target, indices, batch: int,
                    sync: bool = True) -> dict:
        """One epoch on device.  ``sync=False`` returns device arrays
        without a host readback — on tunneled TPUs a device→host fetch
        costs ~100× a step, so throughput loops should defer syncing."""
        if self._train_epoch_fn is None:
            self._build()
        idx, mask = self._idx_matrix(np.asarray(indices), batch)
        self.params, self.vels, ms = self._train_epoch_fn(
            self.params, self.vels, data, target, idx, mask)
        return {k: np.asarray(v) for k, v in ms.items()} if sync else ms

    def eval_epoch(self, data, target, indices, batch: int,
                   sync: bool = True) -> dict:
        if self._eval_epoch_fn is None:
            self._build()
        idx, mask = self._idx_matrix(np.asarray(indices), batch)
        ms = self._eval_epoch_fn(self.params, data, target, idx, mask)
        return {k: np.asarray(v) for k, v in ms.items()} if sync else ms

    # -- sync back into the unit graph ------------------------------------
    def write_back(self) -> None:
        """Install trained params into the workflow's unit Vectors."""
        if self.workflow is None:
            return
        for fwd, gdu, (w, b), (vw, vb) in zip(
                self.workflow.forwards, self.workflow.gds, self.params,
                self.vels):
            fwd.weights.mem = np.asarray(w)
            if b is not None:
                fwd.bias.mem = np.asarray(b)
            gdu.velocity_weights.mem = np.asarray(vw)
            if vb is not None:
                gdu.velocity_bias.mem = np.asarray(vb)
