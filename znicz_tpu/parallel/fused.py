"""Fused train step: the whole unit chain as one jitted function.

This is the TPU-native execution model (SURVEY.md §7): the unit graph built
by ``StandardWorkflow`` stays the assembly/testing surface, while this
module compiles the SAME math — forward chain + evaluator + hand-written
backward chain + SGD update — into one ``jit``-ted, mesh-shardable step,
eliminating the per-minibatch Python dispatch the reference paid
(SURVEY.md §3.1 hot-loop note).  A whole epoch runs as a ``lax.scan`` over
a precomputed index matrix with the dataset HBM-resident, so the host
touches the device once per epoch, not once per unit per minibatch.

Layer coverage matches the unit zoo: fc (All2All*), conv (Conv*), the
pooling family, LRN, dropout, and standalone activations.  Stochastic
layers (dropout, stochastic pooling) draw from the same counter-based RNG
as the units, keyed by (unit, epoch, samples-consumed) — so the fused path
reproduces the unit-graph path bit-for-bit even through randomness
(SURVEY.md §7 hard part (c)).

Gradient aggregation across the ``data`` mesh axis is the all-reduce XLA
inserts automatically for the sharded batch dim — the TPU replacement for
the reference's ``apply_data_from_slave`` fold [baseline]."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import (activations, conv as conv_ops, deconv as deconv_ops,
                   dropout as drop_ops, lrn_pool as lrn_pool_ops,
                   normalization as lrn_ops, pooling as pool_ops,
                   softmax as softmax_ops)
from . import mesh as mesh_lib

#: Layer kinds with trainable parameters.
PARAM_KINDS = ("fc", "conv", "deconv")


@dataclasses.dataclass(frozen=True)
class LayerSpec:
    kind: str                     # fc | conv | max_pool | maxabs_pool |
    #                               avg_pool | stochastic_pool |
    #                               stochastic_abs_pool | lrn | lrn_pool |
    #                               dropout | activation
    activation: str               # activations.BY_NAME key; last fc layer
    include_bias: bool            # of a softmax model keeps "linear"
    hypers: tuple                 # (lr, weights_decay, l1_vs_l2, momentum)
    hypers_bias: tuple
    config: tuple = ()            # static kind-specific kv pairs (sorted)

    @property
    def cfg(self) -> dict:
        return dict(self.config)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    layers: tuple[LayerSpec, ...]
    loss: str                     # "softmax" | "mse"
    compute_dtype: str = "float32"
    #: dtype activations are STORED in between layers (and therefore in
    #: the backward caches).  "bfloat16" halves the dominant HBM traffic
    #: of activation-bound nets (AlexNet's LRN/pool stack) while master
    #: params, gradients and the loss head stay f32 — the TPU-native
    #: mixed-precision recipe.  Default f32 keeps every bit-exact
    #: backend-equivalence contract intact.
    storage_dtype: str = "float32"
    #: per-spec-row index into ``workflow.forwards``/``workflow.gds`` —
    #: the write-back map.  The lrn_pool merge makes spec rows FEWER
    #: than forward units, so a positional zip would install weights on
    #: the wrong units; extract_model always fills this.  Empty ()
    #: (hand-built specs with no workflow) means identity.
    unit_index: tuple = ()

    def __post_init__(self):
        # the softmax-CE head consumes 2D logits and backward() hands the
        # last layer a pre-activation error — only well-defined for a
        # final fc layer; the MSE head accepts any output shape
        if (self.loss == "softmax" and self.layers
                and self.layers[-1].kind != "fc"):
            raise NotImplementedError(
                f"the fused softmax path requires a final fc layer (got "
                f"{self.layers[-1].kind!r}); use the unit-graph path for "
                f"other heads")
        for layer in self.layers:
            act = activations.BY_NAME[layer.activation]
            if act.needs_input and layer.kind in PARAM_KINDS:
                # fc/conv cache only the layer *input*, not the
                # pre-activation tensor these derivatives need; use a
                # standalone activation layer (which is supported) or the
                # unit-graph path.
                raise NotImplementedError(
                    f"activation {layer.activation!r} fused into a "
                    f"{layer.kind} layer needs its pre-activation input "
                    f"for the backward pass; insert it as a standalone "
                    f"'activation' layer instead")

    def act(self, i: int):
        return activations.BY_NAME[self.layers[i].activation]


def extract_model(workflow) -> tuple[ModelSpec, list, list]:
    """Read (spec, params, velocities) out of an initialized
    StandardWorkflow.  params/velocities: list of (w, b) numpy pairs,
    ``(None, None)`` for parameter-less layers."""
    from ..nn import activation as act_units
    from ..nn.all2all import All2All, All2AllSoftmax
    from ..nn.conv import Conv
    from ..nn.deconv import Deconv
    from ..nn.depooling import Depooling
    from ..nn.dropout import DropoutForward
    from ..nn.normalization import LRNormalizerForward
    from ..nn import pooling as pool_units

    layers, params, vels = [], [], []
    for fwd, gdu in zip(workflow.forwards, workflow.gds):
        if getattr(gdu, "accumulate_gradient", False) \
                or not getattr(gdu, "apply_gradient", True):
            # manual gradient-accumulation schedules configured on the
            # GD units have no per-unit expression in the fused step —
            # silently training with per-step updates would diverge
            # from the unit graph.  The fused-path equivalent is
            # FusedTrainer(accum_steps=k).
            raise NotImplementedError(
                f"{gdu.name}: accumulate_gradient/apply_gradient "
                "schedules need the unit-graph path (wf.run()); for "
                "fused accumulation clear those unit flags and use "
                "FusedTrainer(spec, params, vels, accum_steps=k) — "
                "extract_model cannot translate a per-unit schedule")
        hypers = (getattr(gdu, "learning_rate", 0.0),
                  getattr(gdu, "weights_decay", 0.0),
                  getattr(gdu, "l1_vs_l2", 0.0),
                  getattr(gdu, "gradient_moment", 0.0))
        hypers_bias = (getattr(gdu, "learning_rate_bias", 0.0),
                       getattr(gdu, "weights_decay_bias", 0.0),
                       getattr(gdu, "l1_vs_l2_bias", 0.0),
                       getattr(gdu, "gradient_moment_bias", 0.0))
        act = "linear"
        config: dict = {}
        has_params = False
        if isinstance(fwd, All2All):
            kind = "fc"
            has_params = True
            act = ("linear" if isinstance(fwd, All2AllSoftmax)
                   else fwd.ACTIVATION.name)
        elif isinstance(fwd, Conv):
            kind = "conv"
            has_params = True
            act = fwd.ACTIVATION.name
            config = {"stride": fwd.sliding, "padding": fwd.padding}
        elif isinstance(fwd, Deconv):
            kind = "deconv"
            act = fwd.ACTIVATION.name
            config = {"stride": fwd.sliding, "padding": fwd.padding}
            if fwd.conv_unit is not None:
                # tied weights: one shared Vector, updated by both GD
                # units.  The fused step stores the array once (at the
                # encoder conv's index) and replays the unit graph's
                # SEQUENTIAL update order (apply_updates walks layers in
                # reverse, so the deconv's update lands before the conv's
                # reads W for its decay term — exactly the GD chain's
                # execution order).  The deconv keeps its own velocity.
                if fwd.include_bias:
                    raise NotImplementedError(
                        "weight-tied Deconv with include_bias=True is "
                        "not supported by the fused path")
                tie = workflow.forwards.index(fwd.conv_unit)
                if any(la.kind in PARAM_KINDS for la in layers[:tie]):
                    # the unit graph propagates err below the tied conv
                    # through the DECONV-UPDATED shared W (gd_deconv ran
                    # first); the fused backward computes all grads from
                    # pre-update params, so those nets would silently
                    # diverge — refuse instead
                    raise NotImplementedError(
                        "fused path supports weight-tied Deconv only "
                        "when no trainable layer sits below the tied "
                        "encoder conv (err_input there would need the "
                        "mid-backward updated W); use the unit-graph "
                        "path")
                config["tie"] = tie
            else:
                has_params = True
        elif isinstance(fwd, Depooling):
            kind = "depooling"
            config = {"ksize": fwd.ksize, "stride": fwd.sliding,
                      "padding": fwd.padding,
                      "tie": workflow.forwards.index(fwd.pool_unit)}
        elif isinstance(fwd, pool_units.Pooling):
            kind = {"MaxPooling": "max_pool",
                    "MaxAbsPooling": "maxabs_pool",
                    "AvgPooling": "avg_pool",
                    "StochasticPooling": "stochastic_pool",
                    "StochasticAbsPooling": "stochastic_abs_pool",
                    }[type(fwd).__name__]
            config = {"ksize": fwd.ksize, "stride": fwd.sliding,
                      "padding": fwd.padding}
            if kind.startswith("stochastic"):
                config.update(unit_id=fwd.unit_id,
                              seed=fwd.rng.stream_seed)
        elif isinstance(fwd, LRNormalizerForward):
            kind = "lrn"
            config = {"n": fwd.n, "alpha": fwd.alpha, "beta": fwd.beta,
                      "k": fwd.k}
        elif isinstance(fwd, DropoutForward):
            kind = "dropout"
            config = {"ratio": fwd.dropout_ratio, "unit_id": fwd.unit_id,
                      "seed": fwd.rng.stream_seed}
        elif isinstance(fwd, act_units.ActivationForward):
            kind = "activation"
            act = fwd.ACTIVATION.name
        else:
            raise NotImplementedError(
                f"fused path does not support {type(fwd).__name__}")
        layers.append(LayerSpec(
            kind=kind, activation=act,
            include_bias=has_params and fwd.include_bias,
            hypers=hypers, hypers_bias=hypers_bias,
            config=tuple(sorted(config.items()))))
        if has_params:
            params.append((np.asarray(fwd.weights.mem),
                           np.asarray(fwd.bias.mem) if fwd.include_bias
                           else None))
            vels.append((np.asarray(gdu.velocity_weights.mem),
                         np.asarray(gdu.velocity_bias.mem)
                         if fwd.include_bias else None))
        elif kind == "deconv":          # tied: own velocity, shared W
            params.append((None, None))
            vels.append((np.asarray(gdu.velocity_weights.mem), None))
        else:
            params.append((None, None))
            vels.append((None, None))
    loss = workflow.loss_function
    layers, params, vels, unit_index = _merge_lrn_pool(layers, params,
                                                       vels)
    return (ModelSpec(tuple(layers), loss, unit_index=unit_index),
            params, vels)


def _merge_lrn_pool(layers, params, vels):
    """Collapse adjacent (lrn, max_pool|maxabs_pool) pairs into the fused
    ``lrn_pool`` kind (ops/lrn_pool.py: one HBM pass per direction, the
    round-2 ablation's ~39%-of-step lever).  Bit-identical to the split
    layers by construction (same window math, same flat tap order), so
    the merge is on by default; ZNICZ_TPU_LRN_POOL=split keeps the split
    layers (A/B lever).  ``tie`` indices (weight-tied deconv, depooling)
    are remapped; a depooling tied to a merged pool keeps working — the
    merged layer's aux IS the pool's winner-offset tensor."""
    from ..ops import tuning
    identity = tuple(range(len(layers)))
    if not tuning.lrn_pool_merge():
        return layers, params, vels, identity
    out_l, out_p, out_v = [], [], []
    src = []          # spec row → ORIGINAL forwards index (write_back)
    idx_map = {}
    i = 0
    while i < len(layers):
        la = layers[i]
        if (i + 1 < len(layers) and la.kind == "lrn"
                and layers[i + 1].kind in ("max_pool", "maxabs_pool")
                and lrn_pool_ops.fusable(layers[i + 1].cfg["ksize"],
                                         layers[i + 1].cfg["stride"],
                                         layers[i + 1].cfg["padding"])):
            pool = layers[i + 1]
            cfg = dict(la.config)
            cfg.update(pool.config)
            cfg["use_abs"] = pool.kind == "maxabs_pool"
            merged = LayerSpec(
                kind="lrn_pool", activation="linear", include_bias=False,
                hypers=la.hypers, hypers_bias=la.hypers_bias,
                config=tuple(sorted(cfg.items())))
            # fold the PRECEDING conv's activation derivative into the
            # pair backward when its bwd needs only y (y is the pair's
            # input, already in the kernel's VMEM) — kills the separate
            # elementwise sweep over the net's biggest dx tensor
            if out_l and out_l[-1].kind in ("conv", "deconv") \
                    and tuning.lrn_pool_act_fold():
                act = activations.BY_NAME[out_l[-1].activation]
                if out_l[-1].activation != "linear" \
                        and not act.needs_input:
                    cfg["fold_act"] = out_l[-1].activation
                    prev_cfg = dict(out_l[-1].config, act_folded=True)
                    # phase-2 (opt-in): the conv emits the parity
                    # halves directly and takes split gradients back
                    if out_l[-1].kind == "conv" \
                            and tuning.lrn_pool_split_conv():
                        prev_cfg["split_out"] = True
                        cfg["emit_split"] = True
                    out_l[-1] = dataclasses.replace(
                        out_l[-1],
                        config=tuple(sorted(prev_cfg.items())))
                    merged = dataclasses.replace(
                        merged, config=tuple(sorted(cfg.items())))
            idx_map[i] = len(out_l)
            idx_map[i + 1] = len(out_l)   # ties to the pool → merged
            out_l.append(merged)
            out_p.append((None, None))
            out_v.append((None, None))
            src.append(i)                 # paramless: index is nominal
            i += 2
        else:
            idx_map[i] = len(out_l)
            out_l.append(la)
            out_p.append(params[i])
            out_v.append(vels[i])
            src.append(i)
            i += 1
    if len(out_l) == len(layers):
        return layers, params, vels, identity
    remapped = []
    for la in out_l:
        cfg = la.cfg
        if "tie" in cfg:
            cfg["tie"] = idx_map[cfg["tie"]]
            la = dataclasses.replace(la, config=tuple(sorted(cfg.items())))
        remapped.append(la)
    return remapped, out_p, out_v, tuple(src)


# -- pure math (all traced; spec is static) --------------------------------
def forward(spec: ModelSpec, params, x, *, want_caches: bool,
            train: bool = False, epoch=0, ctr=0):
    """Returns (net_output_pre_loss, caches).

    For softmax loss the last layer's output is the *logits* (loss fusion
    happens in the step).  ``caches[i]`` = (layer input, kind-specific
    residual: pooling winner slots; LRN denoms and dropout masks are
    rematerialized in the backward, not cached).
    ``epoch``/``ctr`` (may be traced) feed the counter RNG of stochastic
    layers when ``train``."""
    cdt = jnp.dtype(spec.compute_dtype)
    sdt = jnp.dtype(spec.storage_dtype)
    h = x
    caches = []
    auxes = []       # per-layer residuals, kept even without caches so
    in_shapes = []   # decoder layers can reach their tied encoder layer
    n = len(spec.layers)
    for i, (layer, (w, b)) in enumerate(zip(spec.layers, params)):
        x_in, aux = h, None
        if isinstance(h, tuple):     # split-out conv → pair handoff:
            b_, h_, we, c_ = h[0].shape          # record logical shape
            in_shapes.append((b_, h_, we + h[1].shape[2], c_))
        else:
            in_shapes.append(tuple(h.shape))
        cfg = layer.cfg
        is_last = i == n - 1
        if layer.kind == "fc":
            pre = jnp.dot(h.reshape(h.shape[0], -1).astype(cdt),
                          w.astype(cdt),
                          preferred_element_type=jnp.float32)
            if b is not None:
                pre = pre + b
            if is_last and spec.loss == "softmax":
                h = pre                   # logits; softmax fused with CE
            else:
                h = spec.act(i).fwd(pre, jnp)
        elif layer.kind == "conv":
            if cfg.get("split_out"):
                # phase-2: emit the column-parity halves the merged
                # pair consumes — the split pass over the conv output
                # never exists (ops/conv.py parity decomposition)
                pe, po = conv_ops.xla_conv2d_split(
                    h.astype(cdt), w.astype(cdt), cfg["stride"],
                    cfg["padding"], out_dtype=jnp.float32)
                if b is not None:
                    pe, po = pe + b, po + b
                h = (spec.act(i).fwd(pe, jnp), spec.act(i).fwd(po, jnp))
            else:
                pre = conv_ops.conv2d(h.astype(cdt), w.astype(cdt),
                                      cfg["stride"], cfg["padding"],
                                      out_dtype=jnp.float32)
                if b is not None:
                    pre = pre + b
                h = spec.act(i).fwd(pre, jnp)
        elif layer.kind == "deconv":
            wt = w if w is not None else params[cfg["tie"]][0]
            pre = deconv_ops.deconv2d(h.astype(cdt), wt.astype(cdt),
                                      cfg["stride"], cfg["padding"],
                                      out_dtype=jnp.float32)
            if b is not None:
                pre = pre + b
            h = spec.act(i).fwd(pre, jnp)
        elif layer.kind == "depooling":
            off = auxes[cfg["tie"]]
            h = pool_ops.depooling(
                h, off, in_shapes[cfg["tie"]], cfg["ksize"],
                cfg["stride"], cfg["padding"])
            aux = off
        elif layer.kind == "max_pool":
            h, aux = pool_ops.max_pooling(h, cfg["ksize"],
                                          cfg["stride"], cfg["padding"])
        elif layer.kind == "maxabs_pool":
            h, aux = pool_ops.maxabs_pooling(h, cfg["ksize"],
                                             cfg["stride"],
                                             cfg["padding"])
        elif layer.kind == "avg_pool":
            h = pool_ops.xla_avg_pooling(h, cfg["ksize"], cfg["stride"],
                                         cfg["padding"])
        elif layer.kind in ("stochastic_pool", "stochastic_abs_pool"):
            use_abs = layer.kind == "stochastic_abs_pool"
            if train:
                oshape = pool_ops.pool_out_shape(
                    h.shape, cfg["ksize"], cfg["stride"], cfg["padding"])
                u = pool_ops.stochastic_uniform(
                    cfg["seed"], (cfg["unit_id"], epoch, ctr), oshape,
                    jnp)
                h, aux = pool_ops.xla_stochastic_pooling(
                    h, cfg["ksize"], cfg["stride"], cfg["padding"], u,
                    use_abs=use_abs, deterministic=False)
            else:
                h, aux = pool_ops.xla_stochastic_pooling(
                    h, cfg["ksize"], cfg["stride"], cfg["padding"], None,
                    use_abs=use_abs, deterministic=True)
        elif layer.kind == "lrn":
            # aux stays None: the backward recomputes the denominator
            # from the cached x_in (LRN is HBM-bound; caching the
            # activation-sized d costs more than the windowed VPU sum
            # that rebuilds it — same remat rationale as dropout masks)
            h = lrn_ops.lrn_y(h, cfg["n"], cfg["alpha"],
                              cfg["beta"], cfg["k"])
        elif layer.kind == "lrn_pool":
            # fused pair: the LRN output never touches HBM — the kernel
            # normalizes in VMEM and pools in the same pass; aux is the
            # pool's winner-offset tensor (depooling-tie compatible).
            # With the activation folded, NOTHING downstream needs the
            # unsplit x (the conv below skips its activation backward),
            # so the cache keeps the column-parity halves the kernel
            # consumed — the backward never re-splits x
            if "fold_act" in cfg:
                xe, xo = (h if isinstance(h, tuple)   # split-out conv
                          else lrn_pool_ops.split_cols(h))
                x_in = (xe, xo)
                h, aux = lrn_pool_ops.lrn_maxpool_split(
                    xe, xo, cfg["n"], cfg["alpha"], cfg["beta"],
                    cfg["k"], cfg["ksize"], cfg["stride"],
                    cfg["padding"], cfg["use_abs"])
            else:
                h, aux = lrn_pool_ops.lrn_maxpool(
                    h, cfg["n"], cfg["alpha"], cfg["beta"], cfg["k"],
                    cfg["ksize"], cfg["stride"], cfg["padding"],
                    cfg["use_abs"])
        elif layer.kind == "dropout":
            if train:
                # aux stays None: the backward REGENERATES the mask from
                # the same (seed, counters) — a counter-RNG mask is pure
                # function of its coordinates, so caching an
                # activation-sized buffer through the scan would only
                # add HBM liveness (same fix as the unit path's Pallas
                # dropout, ADVICE round 1)
                h = h * drop_ops.make_mask(
                    cfg["seed"], (cfg["unit_id"], epoch, ctr),
                    tuple(h.shape), cfg["ratio"], jnp)
            # eval: inverted dropout → identity
        elif layer.kind == "activation":
            h = spec.act(i).fwd(h, jnp)
        else:
            raise NotImplementedError(layer.kind)
        if sdt != jnp.float32 and not is_last:
            # storage cast between layers: the next layer's input (and
            # its backward cache) live in sdt; the last layer's output
            # stays f32 so the loss head and its error are full
            # precision
            h = (tuple(t.astype(sdt) for t in h)
                 if isinstance(h, tuple) else h.astype(sdt))
        auxes.append(aux)
        if want_caches:
            caches.append((x_in, aux))
    return h, caches


def predict(spec: ModelSpec, params, x):
    out, _ = forward(spec, params, x, want_caches=False, train=False)
    if spec.loss == "softmax":
        return jax.nn.softmax(out, axis=1)
    return out


def _loss_and_err(spec: ModelSpec, out, target, mask):
    """(mean loss, err w.r.t. last pre-activation, n_err); ``mask`` is a
    per-row 0/1 vector zeroing the wrap-padded tail of a short final
    minibatch, so fused metrics/gradients match the unit-graph exactly."""
    bs = jnp.maximum(jnp.sum(mask), 1.0)
    if spec.loss == "softmax":
        # dispatcher: fused Pallas softmax-CE kernel on TPU, XLA otherwise
        probs, loss, err = softmax_ops.softmax_ce_from_logits(out, target)
        n_err = jnp.sum((jnp.argmax(probs, axis=1) != target) * mask)
        return (jnp.sum(loss * mask) / bs, err * mask[:, None] / bs,
                n_err.astype(jnp.int32))
    mask_b = mask.reshape((-1,) + (1,) * (out.ndim - 1))
    diff = (out - target.reshape(out.shape)) * mask_b
    feats = int(np.prod(out.shape[1:]))
    loss = jnp.sum(diff * diff) / (bs * feats)
    # err w.r.t. the activated output, scaled 1/batch (matches
    # EvaluatorMSE); train_minibatch folds it through the last activation
    return loss, diff / bs, jnp.zeros((), jnp.int32)


def backward(spec: ModelSpec, params, caches, out, err, epoch=0, ctr=0,
             train=True):
    """Hand-written gradient chain (same math as the GD* units).

    ``err`` on entry: w.r.t. the last layer's pre-activation (softmax
    fused with CE; MSE pre-folded by the caller).  ``epoch``/``ctr``
    re-key the dropout counter RNG — masks are regenerated here, not
    cached, so they MUST match the forward's coordinates; pass
    ``train=False`` when the caches came from an eval-mode forward
    (dropout was an identity there, so err passes through)."""
    cdt = jnp.dtype(spec.compute_dtype)
    grads = [None] * len(spec.layers)
    n = len(spec.layers)
    for i in reversed(range(n)):
        layer = spec.layers[i]
        w, b = params[i]
        x_in, aux = caches[i]
        y_i = caches[i + 1][0] if i < n - 1 else out
        cfg = layer.cfg
        slot = _grad_slot(layer, params, i)
        if slot is not None:
            w = slot[0]                # tied deconv: encoder weights
            # fold through the fused activation (last layer already is
            # pre-activation — see docstring); act_folded: the merged
            # lrn_pool ABOVE already applied this derivative in-kernel
            # and returned a full-shape dx (y_i may be its split-halves
            # cache tuple — never consumed here)
            if i == n - 1 or cfg.get("act_folded"):
                err_pre = err
            else:
                err_pre = spec.act(i).bwd(err.reshape(y_i.shape), y_i,
                                          None, jnp)
            if layer.kind == "fc":
                x2 = x_in.reshape(x_in.shape[0], -1)
                err2 = err_pre.reshape(x2.shape[0], -1)
                gw = jnp.dot(x2.astype(cdt).T, err2.astype(cdt),
                             preferred_element_type=jnp.float32)
                gb = jnp.sum(err2, axis=0) if b is not None else None
                err = jnp.dot(err2.astype(cdt), w.astype(cdt).T,
                              preferred_element_type=jnp.float32
                              ).reshape(x_in.shape)
            elif layer.kind == "conv":
                # grads accumulate in f32 (preferred_element_type inside
                # the conv ops); cdt only feeds the MXU operands
                if cfg.get("split_out"):
                    # phase-2: err arrives as the pair's parity halves
                    # (never interleaved) — parity-decomposed grads
                    ee, eo = (e.astype(cdt) for e in err_pre)
                    gw = conv_ops.xla_conv2d_grad_weights_split(
                        x_in.astype(cdt), ee, eo, w.shape,
                        cfg["stride"], cfg["padding"])
                    gb = (jnp.sum(err_pre[0], axis=(0, 1, 2))
                          + jnp.sum(err_pre[1], axis=(0, 1, 2))
                          if b is not None else None)
                    err = conv_ops.xla_conv2d_grad_input_split(
                        ee, eo, w.astype(cdt), x_in.shape,
                        cfg["stride"], cfg["padding"])
                else:
                    gw = conv_ops.conv2d_grad_weights(
                        x_in.astype(cdt), err_pre.astype(cdt), w.shape,
                        cfg["stride"], cfg["padding"])
                    gb = (jnp.sum(err_pre, axis=(0, 1, 2))
                          if b is not None else None)
                    err = conv_ops.conv2d_grad_input(
                        err_pre.astype(cdt), w.astype(cdt), x_in.shape,
                        cfg["stride"], cfg["padding"])
            else:                                         # deconv
                gw = deconv_ops.deconv2d_grad_weights(
                    err_pre.astype(cdt), x_in.astype(cdt), w.shape,
                    cfg["stride"], cfg["padding"])
                gb = (jnp.sum(err_pre, axis=(0, 1, 2))
                      if b is not None else None)
                err = deconv_ops.deconv2d_grad_input(
                    err_pre.astype(cdt), w.astype(cdt), cfg["stride"],
                    cfg["padding"])
            grads[i] = (gw, gb)
        elif layer.kind in ("max_pool", "maxabs_pool", "stochastic_pool",
                           "stochastic_abs_pool"):
            err = pool_ops.gd_max_pooling(
                err.reshape(y_i.shape), aux, x_in.shape, cfg["ksize"],
                cfg["stride"], cfg["padding"])
        elif layer.kind == "avg_pool":
            err = pool_ops.xla_gd_avg_pooling(
                err.reshape(y_i.shape), x_in.shape, cfg["ksize"],
                cfg["stride"], cfg["padding"])
        elif layer.kind == "lrn":
            err = lrn_ops.gd_lrn_x(err.reshape(y_i.shape), x_in,
                                   cfg["n"], cfg["alpha"], cfg["beta"],
                                   cfg["k"])
        elif layer.kind == "lrn_pool":
            # fused pair backward: pooled err scatters through the
            # winner offsets and folds through the LRN derivative (and
            # optionally the preceding conv's activation derivative) in
            # one kernel — err_y never materializes
            if isinstance(x_in, tuple):      # split-halves cache (fold)
                err = lrn_pool_ops.gd_lrn_maxpool_split(
                    err.reshape(y_i.shape), aux, x_in[0], x_in[1],
                    cfg["n"], cfg["alpha"], cfg["beta"], cfg["k"],
                    cfg["ksize"], cfg["stride"], cfg["padding"],
                    cfg.get("fold_act"),
                    return_split=bool(cfg.get("emit_split")))
            else:
                err = lrn_pool_ops.gd_lrn_maxpool(
                    err.reshape(y_i.shape), aux, x_in, cfg["n"],
                    cfg["alpha"], cfg["beta"], cfg["k"], cfg["ksize"],
                    cfg["stride"], cfg["padding"],
                    cfg.get("fold_act"))
        elif layer.kind == "depooling":
            err = pool_ops.gd_depooling(
                err.reshape(y_i.shape), aux, cfg["ksize"], cfg["stride"],
                cfg["padding"])
        elif layer.kind == "dropout":
            if train:
                # regenerate the forward's mask (identical counters →
                # bit-identical draw)
                err = err.reshape(x_in.shape) * drop_ops.make_mask(
                    cfg["seed"], (cfg["unit_id"], epoch, ctr),
                    tuple(x_in.shape), cfg["ratio"], jnp)
        elif layer.kind == "activation":
            err = spec.act(i).bwd(err.reshape(y_i.shape), y_i, x_in, jnp)
        else:
            raise NotImplementedError(layer.kind)
    return grads


def apply_updates(spec: ModelSpec, params, vels, grads, lr_scale=1.0,
                  lr_scale_bias=None):
    # Inline update math (not the Pallas update kernel): inside the fused
    # step XLA fuses these elementwise ops into the surrounding graph; the
    # Pallas kernel serves the unit-graph path where each op dispatches
    # separately (the reference's kernel-per-op model).
    # ``lr_scale`` may be traced — LR schedules never force a recompile.
    #
    # Layers apply in REVERSE order — the GD chain's execution order
    # (last forward's GD runs first).  For independent parameters the
    # order is irrelevant; for weight-tied Deconv it makes the shared
    # Vector's two sequential updates land exactly as the unit graph's:
    # the deconv's update first, then the conv's decay term reads the
    # already-updated W.
    if lr_scale_bias is None:
        lr_scale_bias = lr_scale
    n = len(spec.layers)
    cur_w = [p[0] for p in params]
    cur_b = [p[1] for p in params]
    new_v = [list(v) for v in vels]
    for i in reversed(range(n)):
        layer, grad = spec.layers[i], grads[i]
        if grad is None:
            continue
        tgt = layer.cfg.get("tie", i) if layer.kind == "deconv" else i
        w, b = cur_w[tgt], cur_b[i]
        if w is None:
            continue
        gw, gb = grad
        vw, vb = vels[i]
        lr, wd, l1, mom = layer.hypers
        reg = wd * ((1.0 - l1) * w + 0.5 * l1 * jnp.sign(w))
        vw2 = mom * vw - lr * lr_scale * (gw + reg)
        cur_w[tgt] = w + vw2
        new_v[i][0] = vw2
        if b is not None:
            lrb, wdb, l1b, momb = layer.hypers_bias
            regb = wdb * ((1.0 - l1b) * b + 0.5 * l1b * jnp.sign(b))
            vb2 = momb * vb - lrb * lr_scale_bias * (gb + regb)
            cur_b[i] = b + vb2
            new_v[i][1] = vb2
    return ([(w, b) for w, b in zip(cur_w, cur_b)],
            [tuple(v) for v in new_v])


def grad_minibatch(spec: ModelSpec, params, x, target, mask=None,
                   epoch=0, ctr=0):
    """(grads, metrics) of one minibatch — train_minibatch without the
    update, the building block gradient accumulation composes."""
    if mask is None:
        mask = jnp.ones((x.shape[0],), jnp.float32)
    out, caches = forward(spec, params, x, want_caches=True, train=True,
                          epoch=epoch, ctr=ctr)
    loss, err, n_err = _loss_and_err(spec, out, target, mask)
    last = len(spec.layers) - 1
    if spec.loss == "mse" and spec.layers[last].kind in PARAM_KINDS:
        # backward() expects pre-activation err at a param layer; other
        # last-layer kinds fold their own activation in backward()
        err = spec.act(last).bwd(err, out, None, jnp)
    grads = backward(spec, params, caches, out, err, epoch=epoch,
                     ctr=ctr)
    return grads, {"loss": loss, "n_err": n_err}


def _grad_slot(layer: LayerSpec, params, i: int):
    """(w, b) a layer's gradient entry is shaped like, or None for
    gradient-less layers — THE single definition of backward()'s
    gradient structure (tied deconv: grads live at the deconv's own
    index, shaped like the shared encoder weights)."""
    w, b = params[i]
    if layer.kind in PARAM_KINDS and (w is not None
                                      or layer.kind == "deconv"):
        if layer.kind == "deconv" and w is None:
            w = params[layer.cfg["tie"]][0]
        return w, b
    return None


def grad_zeros(spec: ModelSpec, params):
    """Zero accumulator matching backward()'s gradient structure
    (f32 — the accumulation dtype regardless of storage/compute)."""
    zs = []
    for i, layer in enumerate(spec.layers):
        slot = _grad_slot(layer, params, i)
        if slot is None:
            zs.append(None)
        else:
            w, b = slot
            zs.append((jnp.zeros(w.shape, jnp.float32),
                       jnp.zeros(b.shape, jnp.float32)
                       if b is not None else None))
    return zs


def train_minibatch(spec: ModelSpec, params, vels, x, target, mask=None,
                    epoch=0, ctr=0, lr_scale=1.0, lr_scale_bias=None):
    grads, metrics = grad_minibatch(spec, params, x, target, mask,
                                    epoch=epoch, ctr=ctr)
    params, vels = apply_updates(spec, params, vels, grads, lr_scale,
                                 lr_scale_bias)
    return params, vels, metrics


def eval_minibatch(spec: ModelSpec, params, x, target, mask=None):
    if mask is None:
        mask = jnp.ones((x.shape[0],), jnp.float32)
    out, _ = forward(spec, params, x, want_caches=False, train=False)
    loss, _, n_err = _loss_and_err(spec, out, target, mask)
    return {"loss": loss, "n_err": n_err}


class FusedTrainer:
    """Owns device-resident params and compiled epoch functions.

    ``mesh``: optional ``jax.sharding.Mesh`` with ("data", "model") axes —
    params get TP shardings (mesh.shard_params), batches shard over
    ``data``; XLA inserts the gradient all-reduce.  With no mesh,
    single-device jit."""

    def __init__(self, workflow=None, spec: ModelSpec | None = None,
                 params=None, vels=None, mesh=None, accum_steps: int = 1,
                 augment=None):
        if workflow is not None:
            spec, params, vels = extract_model(workflow)
        self.spec = spec
        self.mesh = mesh
        self.workflow = workflow
        #: optional loader.augment.RandomCropFlip applied ON DEVICE
        #: inside the epoch scan (device_apply): the resident path's
        #: ImageNet recipe — data stays at decode size in HBM, crops
        #: ride the scan.  Bit-identical to the streaming loaders'
        #: host-side augmentation for the same (seed, epoch, row).
        self.augment = augment
        #: micro-batch gradient accumulation: gradients of ``k``
        #: consecutive minibatches SUM before one update — the fused
        #: equivalent of the unit graph's accumulate_gradient +
        #: deferred apply_gradient (nn_units.py), for effective batches
        #: beyond what HBM fits in one forward.  The summed gradient is
        #: applied unscaled, exactly like the unit semantics (fold any
        #: 1/k into the learning rate if means are wanted).  A trailing
        #: partial group flushes at the end of EACH train_epoch call —
        #: callers chunking one epoch across calls (run_fused's
        #: deferred-tail pattern) would get different grouping than a
        #: whole-epoch call, so accum>1 expects whole epochs per call.
        if not isinstance(accum_steps, int) or isinstance(
                accum_steps, bool) or accum_steps < 1:
            raise ValueError(f"accum_steps must be a positive int, got "
                             f"{accum_steps!r}")
        self.accum_steps = accum_steps
        if mesh is not None:
            self._param_shardings = []
            pidx = 0   # alternate TP axis over *parameterized* layers only
            for (w, b) in params:
                if w is None:
                    self._param_shardings.append((None, None))
                else:
                    # plan_tp_sharding replicates (instead of crashing
                    # device_put) any layer whose split dim the model
                    # axis doesn't divide — the ONE policy serving's
                    # _tp_shardings shares
                    sh, pidx = mesh_lib.plan_tp_sharding(
                        mesh, pidx, w.shape)
                    self._param_shardings.append(
                        (sh, mesh_lib.replicated(mesh)))
            for j, layer in enumerate(spec.layers):
                # tied deconv: its velocity must shard like the shared W
                if layer.kind == "deconv" and "tie" in layer.cfg:
                    self._param_shardings[j] = \
                        self._param_shardings[layer.cfg["tie"]]
            put = lambda a, s: jax.device_put(a, s)      # noqa: E731
            self.params = [
                (put(w, sh[0]) if w is not None else None,
                 put(b, sh[1]) if b is not None else None)
                for (w, b), sh in zip(params, self._param_shardings)]
            self.vels = [
                (put(vw, sh[0]) if vw is not None else None,
                 put(vb, sh[1]) if vb is not None else None)
                for (vw, vb), sh in zip(vels, self._param_shardings)]
            self._batch_sharding = mesh_lib.shard_batch(mesh)
            self._repl = mesh_lib.replicated(mesh)
        else:
            self.params = jax.device_put(params)
            self.vels = jax.device_put(vels)
            self._batch_sharding = None
        self._train_epoch_fn = None
        self._eval_epoch_fn = None
        self._auto_epoch = 0
        #: _mesh_place memo: id(source) -> (source, placed-on-mesh)
        self._placed: dict = {}

    # -- epoch-granular compiled drivers ----------------------------------
    def _build(self):
        spec = self.spec
        accum = self.accum_steps

        aug = self.augment

        def train_epoch(params, vels, data, target, idx, mask, ctrs,
                        epoch, scales, scales_b):
            # `scales`/`scales_b` = per-STEP lr multipliers for weights
            # and biases (scalar schedules broadcast host-side), so
            # per-minibatch policies (lr_adjust by_epoch=False) and
            # separate bias policies trace in without recompiles
            def gather(step_idx):
                x = jnp.take(data, step_idx, axis=0)
                if self._batch_sharding is not None:
                    x = jax.lax.with_sharding_constraint(
                        x, self._batch_sharding)
                if aug is not None:
                    x = aug.device_apply(x, step_idx, epoch, train=True)
                return x, jnp.take(target, step_idx, axis=0)

            if accum == 1:
                def body(carry, step):
                    params, vels = carry
                    step_idx, step_mask, step_ctr, s_w, s_b = step
                    x, t = gather(step_idx)
                    params, vels, m = train_minibatch(
                        spec, params, vels, x, t, step_mask,
                        epoch=epoch, ctr=step_ctr, lr_scale=s_w,
                        lr_scale_bias=s_b)
                    return (params, vels), m
                (params, vels), ms = jax.lax.scan(
                    body, (params, vels),
                    (idx, mask, ctrs, scales, scales_b))
                return params, vels, ms

            # micro-batch accumulation: grads of `accum` consecutive
            # steps sum in an f32 accumulator; every accum-th step
            # applies ONE update with the sum (unit-graph
            # accumulate_gradient semantics) at that step's lr scale.
            # A trailing partial group at epoch end applies too —
            # deferring it across epochs would silently mix epochs'
            # RNG coordinates.
            zeros = grad_zeros(spec, params)
            n_steps = idx.shape[0]

            def body(carry, step):
                params, vels, acc = carry
                (step_i, step_idx, step_mask, step_ctr, s_w,
                 s_b) = step
                x, t = gather(step_idx)
                grads, m = grad_minibatch(spec, params, x, t, step_mask,
                                          epoch=epoch, ctr=step_ctr)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                last_of_group = ((step_i + 1) % accum == 0) | (
                    step_i + 1 == n_steps)

                def apply(ops):
                    p, v, a = ops
                    p, v = apply_updates(spec, p, v, a, s_w, s_b)
                    return p, v, jax.tree_util.tree_map(
                        jnp.zeros_like, a)

                params, vels, acc = jax.lax.cond(
                    last_of_group, apply, lambda ops: ops,
                    (params, vels, acc))
                return (params, vels, acc), m
            (params, vels, _), ms = jax.lax.scan(
                body, (params, vels, zeros),
                (jnp.arange(n_steps), idx, mask, ctrs, scales,
                 scales_b))
            return params, vels, ms

        def eval_epoch(params, data, target, idx, mask):
            def body(_, step):
                step_idx, step_mask = step
                x = jnp.take(data, step_idx, axis=0)
                t = jnp.take(target, step_idx, axis=0)
                if self._batch_sharding is not None:
                    x = jax.lax.with_sharding_constraint(
                        x, self._batch_sharding)
                if aug is not None:        # eval: center crop
                    x = aug.device_apply(x, step_idx, 0, train=False)
                return None, eval_minibatch(spec, params, x, t, step_mask)
            _, ms = jax.lax.scan(body, None, (idx, mask))
            return ms

        # mesh runs pin out_shardings: params/vels come back in the
        # SAME TP layout they went in (donation can then reuse the
        # buffers in place), metrics come back replicated — and the
        # sharded-batch + sharded-params layout is what makes XLA
        # insert the gradient all-reduce over the ``data`` axis.  The
        # 1x1 / meshless path passes no shardings at all, so the
        # single-device jit is byte-identical to the pre-SPMD build.
        jit_kw: dict = {}
        ejit_kw: dict = {}
        if self._batch_sharding is not None:
            psh = [tuple(s) for s in self._param_shardings]
            jit_kw["out_shardings"] = (psh, psh, self._repl)
            ejit_kw["out_shardings"] = self._repl
        # compile accounting (telemetry.compilestats): jit compiles
        # lazily, so the first train/eval call of a run is where the
        # whole-epoch XLA compile actually lands — time it into
        # compile_time_ms{site="train.fused"} so the MFU work can
        # subtract compile from measured step time
        from ..telemetry import compilestats
        self._train_epoch_fn = compilestats.first_call_timed(
            jax.jit(train_epoch, donate_argnums=(0, 1), **jit_kw),
            site="train.fused", cause="cold")
        self._eval_epoch_fn = compilestats.first_call_timed(
            jax.jit(eval_epoch, **ejit_kw), site="train.fused",
            cause="cold")

    def _mesh_place(self, a):
        """Re-place a whole-epoch tensor onto the mesh (replicated:
        every step gathers its global batch from it by index, then the
        with_sharding_constraint shards the batch over ``data``).  A
        loader's devmem arrives committed to ONE device, which a mesh
        jit rejects as incompatible — host arrays and already-placed
        mesh arrays pass through at no cost.  Meshless: identity.

        The placement memoizes on the SOURCE array object: the fused
        loop hands the same devmem to train/eval several times per
        epoch, and re-replicating the whole dataset each call would
        put O(dataset × devices) transfer traffic on the hot path.
        The memo holds the source too, so an id() can never alias a
        collected array — callers must not mutate a placed source in
        place (loader devmem and the epoch tensors never are)."""
        if self._batch_sharding is None or a is None:
            return a
        if getattr(a, "sharding", None) == self._repl:
            return a
        hit = self._placed.get(id(a))
        if hit is not None and hit[0] is a:
            return hit[1]
        placed = jax.device_put(a, self._repl)
        while len(self._placed) >= 8:     # a handful of epoch tensors
            self._placed.pop(next(iter(self._placed)))
        self._placed[id(a)] = (a, placed)
        return placed

    @staticmethod
    def _step_scales(lr_scale, lr_scale_bias, n_steps: int):
        """Per-step (weight, bias) lr multiplier vectors from scalar or
        array schedules — one definition for resident and streaming."""
        scales = np.broadcast_to(np.asarray(lr_scale, np.float32),
                                 (n_steps,))
        scales_b = scales if lr_scale_bias is None else np.broadcast_to(
            np.asarray(lr_scale_bias, np.float32), (n_steps,))
        return scales, scales_b

    def _idx_matrix(self, indices: np.ndarray, batch: int,
                    ctr_base: int = 0) -> tuple[np.ndarray, np.ndarray,
                                                np.ndarray]:
        """(steps, batch) int32 indices + 0/1 mask + per-step counter.
        The final short batch wraps around for a static shape; the mask
        zeroes the padded tail so metrics and gradients count each sample
        exactly once.  The counter equals the loader's
        ``minibatch_offset`` after the corresponding unit-graph step
        (``ctr_base`` = samples already consumed this epoch by earlier
        calls), so stochastic layers reproduce the unit path's RNG
        draws."""
        n = len(indices)
        steps = max(1, -(-n // batch))
        padded = np.resize(indices, steps * batch)
        mask = np.zeros(steps * batch, np.float32)
        mask[:n] = 1.0
        ctrs = (ctr_base + np.minimum((np.arange(steps) + 1) * batch, n)
                ).astype(np.uint32)
        return (padded.reshape(steps, batch).astype(np.int32),
                mask.reshape(steps, batch), ctrs)

    def train_epoch(self, data, target, indices, batch: int,
                    sync: bool = True, epoch: int | None = None,
                    lr_scale=1.0, ctr_base: int = 0,
                    lr_scale_bias=None) -> dict:
        """One epoch on device.  ``sync=False`` returns device arrays
        without a host readback — on tunneled TPUs a device→host fetch
        costs ~100× a step, so throughput loops should defer syncing.

        ``epoch`` keys the stochastic layers' counter RNG; when omitted
        an internal counter advances per call, so repeated calls never
        silently reuse dropout masks.  ``lr_scale`` multiplies every
        layer's learning rate (traced — LR schedules don't recompile):
        a scalar, or a per-minibatch array of len(steps) for
        iteration-granular policies (lr_adjust by_epoch=False);
        ``lr_scale_bias`` does the same for bias learning rates
        (default: follow ``lr_scale``)."""
        if epoch is None:
            epoch = self._auto_epoch
        self._auto_epoch = epoch + 1
        if self._train_epoch_fn is None:
            self._build()
        data, target = self._mesh_place(data), self._mesh_place(target)
        idx, mask, ctrs = self._idx_matrix(np.asarray(indices), batch,
                                           ctr_base)
        scales, scales_b = self._step_scales(lr_scale, lr_scale_bias,
                                             idx.shape[0])
        self.params, self.vels, ms = self._train_epoch_fn(
            self.params, self.vels, data, target, idx, mask, ctrs,
            jnp.uint32(epoch), jnp.asarray(scales),
            jnp.asarray(scales_b))
        return {k: np.asarray(v) for k, v in ms.items()} if sync else ms

    def eval_epoch(self, data, target, indices, batch: int,
                   sync: bool = True) -> dict:
        if self._eval_epoch_fn is None:
            self._build()
        data, target = self._mesh_place(data), self._mesh_place(target)
        idx, mask, _ = self._idx_matrix(np.asarray(indices), batch)
        ms = self._eval_epoch_fn(self.params, data, target, idx, mask)
        return {k: np.asarray(v) for k, v in ms.items()} if sync else ms

    # -- sync back into the unit graph ------------------------------------
    def write_back(self) -> None:
        """Install trained params into the workflow's unit Vectors.

        Rows are addressed through ``spec.unit_index`` — after the
        lrn_pool merge the spec has FEWER rows than the workflow has
        forward units, so a positional zip would land weights on the
        wrong units (review r3)."""
        if self.workflow is None:
            return
        fwds, gds = self.workflow.forwards, self.workflow.gds
        umap = self.spec.unit_index or tuple(range(len(self.params)))
        for ui, (w, b), (vw, vb) in zip(umap, self.params, self.vels):
            fwd, gdu = fwds[ui], gds[ui]
            if w is not None:
                fwd.weights.mem = np.asarray(w)
                if b is not None:
                    fwd.bias.mem = np.asarray(b)
            if vw is not None:   # tied deconv: own velocity, shared W
                gdu.velocity_weights.mem = np.asarray(vw)
            if vb is not None:
                gdu.velocity_bias.mem = np.asarray(vb)
