"""Distributed execution: mesh-sharded fused training steps.

Replaces the reference's entire master–slave layer (SURVEY.md §2.4:
Twisted TCP control plane + ZeroMQ data plane, pickled tensors,
``apply_data_from_slave`` Python-side aggregation) with the TPU-native
design from the north star: the whole train step (forwards + evaluator +
backward + update) compiles to ONE jitted function laid out over a
``jax.sharding.Mesh``; gradient aggregation is the all-reduce XLA inserts
for the sharded batch dimension, riding ICI.  Multi-host runs bootstrap
via ``jax.distributed`` (DCN coordination) instead of a Twisted server.
"""

from .checkpoint import (TrainerCheckpointer, restore_trainer,
                         save_trainer)
from .fused import (FusedTrainer, ModelSpec, extract_model)
from .mesh import make_mesh, shard_batch, shard_params

__all__ = ["FusedTrainer", "ModelSpec", "extract_model", "make_mesh",
           "shard_batch", "shard_params", "TrainerCheckpointer",
           "save_trainer", "restore_trainer"]
