"""Multi-host SPMD bootstrap + data distribution + failure recovery.

Parity target: the reference's distributed runtime (SURVEY.md §2.1
Master server / Slave client rows; §2.4; §3.2 job-loop call stack;
§5 failure detection): a Twisted TCP + ZeroMQ master–slave star shipping
pickled minibatches and gradients, with disconnect-requeue recovery.

TPU-first redesign (the north star): every host runs the SAME program;
``jax.distributed`` (DCN coordination service) replaces the Twisted
control plane; the data plane is XLA collectives over ICI/DCN inside the
compiled step — no pickled tensors, no job queue.  This module holds the
glue the reference put in server.py/client.py:

* :func:`initialize` — process bootstrap (the master/slave handshake).
* :func:`global_mesh` — a ("data", "model") mesh over ALL processes'
  devices (the slave roster).
* :func:`shard_dataset` — per-process dataset slice → one global sharded
  array (the reference's ``generate_data_for_slave`` minibatch split,
  done once per dataset instead of per job).
* :class:`CheckpointRecovery` — crash/preemption recovery: periodic
  snapshots + resume (the reference's requeue becomes restart-from-
  checkpoint, SURVEY.md §5 failure row).
"""

from __future__ import annotations

import os

import jax
import numpy as np

from . import mesh as mesh_lib


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               retry: "RetryPolicy | None" = None) -> None:
    """Bootstrap multi-host JAX (idempotent).  Arguments may come from
    the environment (JAX_COORDINATOR_ADDRESS / NUM_PROCESSES /
    PROCESS_ID) — the launcher passes CLI flags through here.

    The coordinator handshake is the ``relay.connect`` fault site and
    retries under ``retry`` (default: 3 attempts, 0.5–5 s backoff) —
    on a preempted pod the coordinator routinely comes up seconds
    after its workers, and one refused TCP connect must not kill a
    worker the ElasticRunner would only restart anyway."""
    from ..resilience import faults
    from ..resilience.retry import RetryPolicy
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None:
        return   # single-process: nothing to negotiate
    kwargs = dict(coordinator_address=coordinator)
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    policy = retry if retry is not None else RetryPolicy(
        max_attempts=3, base_delay_s=0.5, max_delay_s=5.0)

    def _connect():
        faults.inject("relay.connect")
        jax.distributed.initialize(**kwargs)

    policy.call(_connect)


def global_mesh(n_model: int = 1) -> "jax.sharding.Mesh":
    """("data", "model") mesh over every device of every process."""
    devices = jax.devices()
    return mesh_lib.make_mesh(n_data=len(devices) // n_model,
                              n_model=n_model, devices=devices)


def process_shard(n: int) -> slice:
    """This process's contiguous row range of an n-sample dataset."""
    p, np_ = jax.process_index(), jax.process_count()
    per = -(-n // np_)
    return slice(p * per, min((p + 1) * per, n))


def shard_dataset(local_rows: np.ndarray, mesh, total_rows: int
                  ) -> jax.Array:
    """Assemble one global batch-sharded array from per-process rows.

    ``local_rows`` are THIS process's samples (``process_shard`` of the
    global set); the result is a global jax.Array sharded over the mesh's
    ``data`` axis — the TPU equivalent of the master shipping each slave
    its minibatch slice, paid once per dataset."""
    sharding = mesh_lib.shard_batch(mesh)
    global_shape = (total_rows,) + tuple(local_rows.shape[1:])
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_rows), global_shape)


def distribute(workflow, mesh) -> dict:
    """Distribute an initialized workflow's per-shard state over ``mesh``
    through the **Distributable protocol** — the SPMD rendition of the
    reference master loop (SURVEY.md §2.1 Distributable row; §3.2):

    for each unit, ``generate_data_for_slave()`` publishes the shard of
    every per-shard array this process owns (``{name: (local_rows,
    total_rows)}``; ``None`` = unit owns only replicated state); the
    'master' role — here just this function, since every process runs
    it symmetrically — assembles one globally batch-sharded jax.Array
    per entry (:func:`shard_dataset`); ``apply_data_from_master``
    installs them back into the unit.  Gradient aggregation (the
    reference's ``apply_data_from_slave`` fold) stays inside the jitted
    step as a psum over the data axis.

    Returns ``{unit_name: [vector names sharded]}`` for logging."""
    out = {}
    for unit in workflow.units:
        payload = unit.generate_data_for_slave()
        if not payload:
            continue
        installed = {
            name: shard_dataset(local, mesh, int(total))
            for name, (local, total) in sorted(payload.items())}
        unit.apply_data_from_master(installed)
        out[unit.name] = sorted(installed)
    return out


class CheckpointRecovery:
    """Failure recovery loop: snapshot every N epochs, resume after a
    crash (reference: master requeued a lost slave's job; with SPMD the
    whole program restarts from the last snapshot — SURVEY.md §5).

    Save and resume retry under ``retry`` (default 3 attempts, short
    backoff): a transient filesystem blip mid-checkpoint is common on
    network mounts, and the atomic single-rename save makes a retry
    always safe — a failed attempt can never leave a torn snapshot
    behind for the retry to trip on."""

    def __init__(self, workflow, directory="snapshots",
                 prefix="recovery", interval=1,
                 retry: "RetryPolicy | None" = None):
        from ..resilience.retry import RetryPolicy
        from ..snapshotter import SnapshotterToFile
        self.workflow = workflow
        self.snap = SnapshotterToFile(workflow, prefix=prefix,
                                      directory=directory,
                                      interval=interval)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.2, max_delay_s=2.0)
        # standalone use: not linked into the control graph
        workflow.units.remove(self.snap) \
            if self.snap in workflow.units else None

    @property
    def path(self) -> str:
        return os.path.join(self.snap.directory,
                            f"{self.snap.prefix}_current.npz")

    def save(self) -> str:
        """Checkpoint now (call between epochs; process 0 writes)."""
        if jax.process_index() != 0:
            return self.path
        return self.retry.call(self.snap.save, "current")

    def resume_if_found(self) -> dict | None:
        """Restore the newest *verified* checkpoint into the
        (initialized) workflow; returns its meta or None when starting
        fresh.  Corrupt entries (torn write, bit rot — see
        znicz_tpu.durability) are quarantined to ``*.corrupt`` and the
        scan falls back to the next-newest verified snapshot: a rotten
        ``current`` must cost one checkpoint interval of progress, not
        the whole run.  Transient read blips still retry under
        ``retry`` as before.  Quarantine/heal writes follow the save
        ownership rule (process 0); other processes scan read-only and
        skip the same corrupt entries."""
        from ..snapshotter import SnapshotterToFile

        def _restore():
            found = SnapshotterToFile.restore(
                self.workflow, directory=self.snap.directory,
                prefix=self.snap.prefix,
                owner=jax.process_index() == 0)
            return found[0] if found is not None else None

        return self.retry.call(_restore)
