"""Mesh construction + sharding rules.

The scaling recipe (How to Scale Your Model): pick a mesh, annotate
shardings, let XLA insert collectives.  Axes:

* ``data``  — batch dimension; gradient aggregation becomes the ICI
  all-reduce XLA inserts (the reference's ``apply_data_from_slave``).
* ``model`` — optional tensor parallelism for wide FC/conv layers:
  alternate layers shard weights on the output / input feature dim, so
  activations stay sharded and XLA inserts reduce-scatter/all-gather
  pairs between layers.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_data: int | None = None, n_model: int = 1,
              devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n_total = len(devices)
    if n_data is None:
        n_data = n_total // n_model
    assert n_data * n_model <= n_total, (n_data, n_model, n_total)
    arr = np.asarray(devices[:n_data * n_model]).reshape(n_data, n_model)
    return Mesh(arr, ("data", "model"))


def shard_batch(mesh: Mesh):
    """Batch tensors: leading dim over ``data``."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def shard_params(mesh: Mesh, layer_index: int, ndim: int):
    """Tensor-parallel weight sharding: even layers split the output
    features, odd layers the input features (Megatron-style pairing, so
    the activation stays sharded across the pair).  With ``model`` axis
    size 1 this degenerates to replication."""
    if mesh.shape["model"] == 1 or ndim < 2:
        return replicated(mesh)
    spec = [None] * ndim
    # fc (in, out): last dim = output features; conv HWIO: last dim =
    # output channels, second-to-last = input channels — same rule
    spec[-1 if layer_index % 2 == 0 else -2] = "model"
    return NamedSharding(mesh, P(*spec))
