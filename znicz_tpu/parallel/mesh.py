"""Mesh construction + sharding rules.

The scaling recipe (How to Scale Your Model): pick a mesh, annotate
shardings, let XLA insert collectives.  Axes:

* ``data``  — batch dimension; gradient aggregation becomes the ICI
  all-reduce XLA inserts (the reference's ``apply_data_from_slave``).
* ``model`` — optional tensor parallelism for wide FC/conv layers:
  alternate layers shard weights on the output / input feature dim, so
  activations stay sharded and XLA inserts reduce-scatter/all-gather
  pairs between layers.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry.registry import REGISTRY

#: mesh adoption visibility: which (dp, tp) layout the live trainer /
#: serving engine actually runs on (1 on both axes = single-device jit)
_axis_size = REGISTRY.gauge(
    "mesh_axis_size",
    "size of the live mesh axis, by axis (data | model) and site "
    "(train | serve); 1/1 means the degenerate single-device path")


def parse_mesh_arg(arg: str) -> tuple[int, int]:
    """``--mesh dp,tp`` → (dp, tp).  A single number means pure data
    parallelism (``--mesh 8`` == ``--mesh 8,1``)."""
    parts = [p.strip() for p in str(arg).split(",") if p.strip()]
    if not 1 <= len(parts) <= 2:
        raise ValueError(f"--mesh expects 'dp' or 'dp,tp', got {arg!r}")
    try:
        dp = int(parts[0])
        tp = int(parts[1]) if len(parts) == 2 else 1
    except ValueError:
        raise ValueError(f"--mesh expects integers, got {arg!r}")
    if dp < 1 or tp < 1:
        raise ValueError(f"--mesh axes must be >= 1, got {arg!r}")
    return dp, tp


def resolve_mesh(mesh_shape, site: str = "train") -> Mesh | None:
    """A ``(dp, tp)`` shape (tuple/list or a ``"dp,tp"`` string) to the
    Mesh the hot paths run on — THE one mesh-adoption policy:

    * ``None`` / ``(1, 1)`` → ``None``: the degenerate single-device
      jit, bit-identical to the pre-mesh behavior (tier-1 on a plain
      CPU host never pays SPMD machinery it didn't ask for);
    * anything else builds the ``("data", "model")`` mesh over the
      first ``dp*tp`` devices and raises if the host has fewer — a
      silently-shrunk mesh would train on a different effective batch
      layout than the operator asked for.

    Records ``mesh_axis_size{axis, site}`` so /metrics and /statusz can
    answer "what layout is this process actually running".
    """
    if mesh_shape is None:
        # restamp like the explicit (1, 1) branch: the gauges answer
        # "what layout is this process RUNNING", and a later meshless
        # run must not keep reporting an earlier run's mesh
        _axis_size.set(1, axis="data", site=site)
        _axis_size.set(1, axis="model", site=site)
        return None
    if isinstance(mesh_shape, str):
        mesh_shape = parse_mesh_arg(mesh_shape)
    if not 1 <= len(mesh_shape) <= 2:
        # same contract as the string form: a 3-axis shape must not
        # silently truncate to a different layout than asked for
        raise ValueError(f"mesh_shape expects (dp,) or (dp, tp), got "
                         f"{tuple(mesh_shape)!r}")
    dp, tp = (int(mesh_shape[0]), int(mesh_shape[1])) \
        if len(mesh_shape) == 2 else (int(mesh_shape[0]), 1)
    if dp < 1 or tp < 1:
        raise ValueError(f"mesh axes must be >= 1, got {(dp, tp)}")
    if dp == tp == 1:
        _axis_size.set(1, axis="data", site=site)
        _axis_size.set(1, axis="model", site=site)
        return None
    n_avail = len(jax.devices())
    if dp * tp > n_avail:
        # refused mesh: the gauges must NOT record it — they answer
        # "what layout is this process actually running", and after
        # this raise the caller is running something else
        raise ValueError(
            f"mesh {dp}x{tp} needs {dp * tp} devices but this host "
            f"exposes {n_avail} (force more on CPU with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    _axis_size.set(dp, axis="data", site=site)
    _axis_size.set(tp, axis="model", site=site)
    return make_mesh(n_data=dp, n_model=tp)


def mesh_shape_of(mesh: Mesh | None) -> tuple[int, int]:
    """(dp, tp) of a mesh, (1, 1) for the single-device path — the
    introspection twin of :func:`resolve_mesh` (healthz/statusz)."""
    if mesh is None:
        return (1, 1)
    return (int(mesh.shape["data"]), int(mesh.shape["model"]))


def make_mesh(n_data: int | None = None, n_model: int = 1,
              devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n_total = len(devices)
    if n_data is None:
        n_data = n_total // n_model
    assert n_data * n_model <= n_total, (n_data, n_model, n_total)
    arr = np.asarray(devices[:n_data * n_model]).reshape(n_data, n_model)
    return Mesh(arr, ("data", "model"))


def shard_batch(mesh: Mesh):
    """Batch tensors: leading dim over ``data``."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def plan_tp_sharding(mesh: Mesh, pidx: int, shape) -> tuple:
    """THE Megatron adoption step both training (FusedTrainer) and
    serving (ServingEngine._tp_shardings) use for one weight tensor:
    returns ``(sharding, next_pidx)``.  Shards via :func:`shard_params`
    at the current pair parity when the split dim is divisible by the
    ``model`` axis; otherwise replicates — and breaks the pair, so the
    next shardable layer restarts at split-output (even parity); its
    activations were gathered at the replicated layer anyway.  One
    definition, so training and serving TP layouts can never drift."""
    n_model = int(mesh.shape["model"])
    if len(shape) >= 2 \
            and shape[-1 if pidx % 2 == 0 else -2] % n_model == 0:
        return shard_params(mesh, pidx, len(shape)), pidx + 1
    return replicated(mesh), pidx + pidx % 2


def shard_params(mesh: Mesh, layer_index: int, ndim: int):
    """Tensor-parallel weight sharding: even layers split the output
    features, odd layers the input features (Megatron-style pairing, so
    the activation stays sharded across the pair).  With ``model`` axis
    size 1 this degenerates to replication."""
    if mesh.shape["model"] == 1 or ndim < 2:
        return replicated(mesh)
    spec = [None] * ndim
    # fc (in, out): last dim = output features; conv HWIO: last dim =
    # output channels, second-to-last = input channels — same rule
    spec[-1 if layer_index % 2 == 0 else -2] = "model"
    return NamedSharding(mesh, P(*spec))
