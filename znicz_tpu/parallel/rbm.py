"""Fused RBM training: whole CD-1 epochs as one jitted ``lax.scan``.

The TPU hot path for the RBM units (same design as ``parallel.som`` for
the Kohonen pair and ``parallel.fused`` for the gradient chain —
SURVEY.md §3.5 non-backprop training pattern): the dataset stays
HBM-resident, an epoch's minibatch index matrix drives a scan whose body
is ``ops.rbm.cd1_momentum_step``, and the host syncs once per epoch.
The per-step RNG counters equal the unit path's (unit_id, epoch,
samples-consumed), so the fused epochs sample the SAME Bernoulli states
as the tick loop — equivalence is testable bit-level."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import rbm as rbm_ops


class FusedRBMTrainer:
    """Device-resident RBM parameters + a compiled CD-1 epoch function.

    ``unit_id``/``seed`` must match the unit-graph trainer's for
    bit-equivalence (pass ``RBMTrainer.unit_id`` and the ``rbm`` stream
    seed)."""

    def __init__(self, w: np.ndarray, vbias: np.ndarray,
                 hbias: np.ndarray, *, seed: int, unit_id: int,
                 learning_rate=0.1, momentum=0.0, weights_decay=0.0):
        self.params = (jnp.asarray(w), jnp.asarray(vbias),
                       jnp.asarray(hbias))
        self.vels = tuple(jnp.zeros_like(p) for p in self.params)
        self.seed = int(seed)
        self.unit_id = int(unit_id)
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.weights_decay = weights_decay
        self._epoch_fn = None

    def _build(self):
        seed, unit_id = self.seed, self.unit_id

        def epoch(params, vels, data, idx, ctrs, epoch_no, lr, mom, wd):
            def body(carry, step):
                params, vels = carry
                step_idx, ctr = step
                v0 = jnp.take(data, step_idx, axis=0)
                v0 = v0.reshape(len(v0), -1)
                params, vels, recon = rbm_ops.cd1_momentum_step(
                    params, vels, v0, lr, mom, wd, seed,
                    (jnp.uint32(unit_id), epoch_no, ctr), jnp)
                return (params, vels), recon
            (params, vels), recons = jax.lax.scan(body, (params, vels),
                                                  (idx, ctrs))
            return params, vels, recons

        self._epoch_fn = jax.jit(epoch, donate_argnums=(0, 1))

    def train_epoch(self, data, indices: np.ndarray, batch: int,
                    epoch: int) -> float:
        """One epoch over ``indices`` (truncated to full batches — the
        scan body needs one static shape); returns mean recon mse."""
        if self._epoch_fn is None:
            self._build()
        steps = len(indices) // batch
        if steps == 0:
            raise ValueError("fewer samples than one batch")
        idx = np.asarray(indices[:steps * batch], np.int32).reshape(
            steps, batch)
        # counters = samples consumed after each step (loader's
        # minibatch_offset in the unit graph)
        ctrs = ((np.arange(steps) + 1) * batch).astype(np.uint32)
        self.params, self.vels, recons = self._epoch_fn(
            self.params, self.vels, data, idx, ctrs, jnp.uint32(epoch),
            jnp.float32(self.learning_rate), jnp.float32(self.momentum),
            jnp.float32(self.weights_decay))
        return float(np.asarray(recons).mean())

    def write_back(self, rbm_unit, trainer_unit=None) -> None:
        """Install trained parameters into the unit graph's Vectors."""
        w, vb, hb = (np.asarray(p) for p in self.params)
        rbm_unit.weights.mem = w
        rbm_unit.vbias.mem = vb
        rbm_unit.hbias.mem = hb
        if trainer_unit is not None:
            vw, vvb, vhb = (np.asarray(v) for v in self.vels)
            trainer_unit.velocity_weights.mem = vw
            trainer_unit.velocity_vbias.mem = vvb
            trainer_unit.velocity_hbias.mem = vhb
