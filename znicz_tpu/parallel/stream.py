"""Streaming fused trainer: disk-backed datasets at fused-path speed.

Counterpart of :class:`parallel.fused.FusedTrainer` for datasets that do
NOT fit in HBM (SURVEY.md §2.2 "Znicz loaders" row — the reference's
on-the-fly/LMDB pipelines).  The resident trainer scans a whole epoch on
device; here the epoch is a host loop over a jitted per-minibatch step,
with :class:`loader.streaming.BatchPrefetcher` double-buffering the
host read/decode + host→HBM transfer under the previous step's compute
(JAX async dispatch keeps the device queue full as long as the host
keeps up).

RNG/math contract: identical to the resident path — the same
``train_minibatch`` body, the same (epoch, samples-consumed) counters —
so a dataset that *does* fit in HBM trains bit-for-bit identically
through either trainer (asserted in tests/test_streaming.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..loader.streaming import BatchPrefetcher, StreamingLoader
from .fused import FusedTrainer, eval_minibatch, train_minibatch


class StreamTrainer(FusedTrainer):
    """FusedTrainer drop-in whose epoch drivers stream minibatches from
    a :class:`StreamingLoader` instead of indexing a resident tensor.

    ``train_epoch(data, target, ...)`` keeps the resident signature so
    ``StandardWorkflow.run_fused`` treats both trainers uniformly;
    ``data``/``target`` are ignored (pass ``None``)."""

    def __init__(self, workflow=None, spec=None, params=None, vels=None,
                 mesh=None, loader: StreamingLoader | None = None,
                 prefetch_depth: int = 2, mse_target: str = "input",
                 accum_steps: int = 1, augment=None,
                 step_callback=None, device_augment: bool = False):
        if augment is not None:
            # streaming augmentation lives on the LOADER (host-side in
            # the prefetch stage) — a trainer-level augment here would
            # double-apply
            raise ValueError("StreamTrainer: set augment on the "
                             "StreamingLoader, not the trainer")
        super().__init__(workflow, spec=spec, params=params, vels=vels,
                         mesh=mesh, accum_steps=accum_steps)
        self.loader = loader if loader is not None \
            else getattr(workflow, "loader", None)
        if not isinstance(self.loader, StreamingLoader):
            raise TypeError("StreamTrainer needs a StreamingLoader")
        self.prefetch_depth = prefetch_depth
        #: for MSE heads: "input" reconstructs x (the autoencoder
        #: default — streaming loaders serve no separate target tensor);
        #: "labels" regresses on the record's label block (arbitrary
        #: label_shape/dtype in .znr shards, e.g. denoising targets)
        if mse_target not in ("input", "labels"):
            raise ValueError(f"mse_target {mse_target!r}")
        self.mse_target = mse_target
        #: x doubles as the target: skip the label decode+transfer too
        self._x_is_target = (self.spec.loss == "mse"
                             and mse_target == "input")
        #: optional ``callback(epoch, step_index)`` invoked after every
        #: streamed micro-step (between accumulation micro-steps too) —
        #: progress reporting, watchdogs, and the failure-parity tests'
        #: mid-group kill point
        self.step_callback = step_callback
        #: move the loader's augmentation policy onto the DEVICE: the
        #: prefetcher ships raw decode-size rows and the jitted step
        #: applies ``policy.device_apply`` (bit-identical pixels to the
        #: host application — same counter-RNG — but the crop runs on
        #: the idle VPU instead of the loader-bound host CPU, which the
        #: --loader bench measured as the augmented pipeline's
        #: bottleneck)
        self.device_augment = bool(device_augment)
        if self.device_augment and getattr(self.loader, "augment",
                                           None) is None:
            raise ValueError("device_augment=True needs an augment "
                             "policy on the StreamingLoader")
        self._step_fn = None
        self._eval_fn = None

    # -- per-minibatch compiled steps -------------------------------------
    def _build_steps(self):
        spec = self.spec
        x_is_target = self._x_is_target
        aug = self.loader.augment if self.device_augment else None

        def step(params, vels, x, t, mask, epoch, ctr, lr_scale,
                 lr_scale_bias, rows):
            if self._batch_sharding is not None:
                x = jax.lax.with_sharding_constraint(
                    x, self._batch_sharding)
            if aug is not None:
                x = aug.device_apply(x, rows, epoch, train=True)
            return train_minibatch(spec, params, vels, x,
                                   x if x_is_target else t, mask,
                                   epoch=epoch, ctr=ctr,
                                   lr_scale=lr_scale,
                                   lr_scale_bias=lr_scale_bias)

        def estep(params, x, t, mask, rows):
            if self._batch_sharding is not None:
                x = jax.lax.with_sharding_constraint(
                    x, self._batch_sharding)
            if aug is not None:
                x = aug.device_apply(x, rows, 0, train=False)
            return eval_minibatch(spec, params, x,
                                  x if x_is_target else t, mask)

        # mesh runs pin out_shardings exactly like FusedTrainer._build:
        # params/vels (and accumulated grads) keep their TP layout
        # across steps, metrics come back replicated; meshless passes
        # nothing and stays the identical single-device jit
        jit_kw: dict = {}
        ejit_kw: dict = {}
        psh = None
        if self._batch_sharding is not None:
            psh = [tuple(s) for s in self._param_shardings]
            jit_kw["out_shardings"] = (psh, psh, self._repl)
            ejit_kw["out_shardings"] = self._repl
        # compile accounting: same contract as FusedTrainer._build —
        # the first streamed step call pays the XLA compile, recorded
        # under its own site so resident and streaming runs are
        # separable in compile_time_ms
        from ..telemetry import compilestats
        self._step_fn = compilestats.first_call_timed(
            jax.jit(step, donate_argnums=(0, 1), **jit_kw),
            site="train.stream", cause="cold")
        self._eval_fn = compilestats.first_call_timed(
            jax.jit(estep, **ejit_kw), site="train.stream", cause="cold")
        if self.accum_steps > 1:
            # gradient accumulation over the streamed step loop: grads
            # per micro-batch, one update per group — the host-loop
            # mirror of FusedTrainer's in-scan grouping (same flush-at-
            # call-end contract)
            from .fused import apply_updates, grad_minibatch

            def gstep(params, x, t, mask, epoch, ctr, rows):
                if self._batch_sharding is not None:
                    x = jax.lax.with_sharding_constraint(
                        x, self._batch_sharding)
                if aug is not None:
                    x = aug.device_apply(x, rows, epoch, train=True)
                return grad_minibatch(spec, params, x,
                                      x if x_is_target else t, mask,
                                      epoch=epoch, ctr=ctr)

            def gapply(params, vels, acc, lr_scale, lr_scale_bias):
                return apply_updates(spec, params, vels, acc, lr_scale,
                                     lr_scale_bias)

            def gadd(acc, grads):
                return jax.tree_util.tree_map(jnp.add, acc, grads)

            gkw: dict = {}
            akw: dict = {}
            ckw: dict = {}
            if psh is not None:
                # grads shard like their params (tied-deconv rows were
                # remapped onto the shared encoder's sharding already)
                # — but gradient-LESS rows are a bare None, not a
                # (None, None) tuple, so the sharding tree must carry
                # None there too (pytree prefix structures must match)
                from .fused import _grad_slot
                gsh = [None if _grad_slot(la, self.params, i) is None
                       else psh[i]
                       for i, la in enumerate(spec.layers)]
                gkw["out_shardings"] = (gsh, self._repl)
                akw["out_shardings"] = (psh, psh)
                ckw["out_shardings"] = gsh
            self._grad_fn = jax.jit(gstep, **gkw)
            # donate only the velocity/accumulator buffers: params are
            # read by every layer's decay term before their new value
            # exists, so XLA can't reuse them and warns
            self._apply_fn = jax.jit(gapply, donate_argnums=(1, 2),
                                     **akw)
            self._acc_add_fn = jax.jit(gadd, donate_argnums=(0,),
                                       **ckw)

    def _device_put(self, a):
        if self._batch_sharding is not None:
            return jax.device_put(a, self._batch_sharding)
        return jax.device_put(a)

    # -- epoch drivers -----------------------------------------------------
    def train_epoch(self, data, target, indices, batch: int,
                    sync: bool = True, epoch: int | None = None,
                    lr_scale=1.0, ctr_base: int = 0,
                    lr_scale_bias=None) -> dict:
        if epoch is None:
            epoch = self._auto_epoch
        self._auto_epoch = epoch + 1
        if self._step_fn is None:
            self._build_steps()
        idx, mask, ctrs = self._idx_matrix(np.asarray(indices), batch,
                                           ctr_base)
        pf = BatchPrefetcher(self.loader, idx, depth=self.prefetch_depth,
                             device_put=self._device_put,
                             skip_labels=self._x_is_target, epoch=epoch,
                             raw=self.device_augment)
        losses, n_errs = [], []
        ep = jnp.uint32(epoch)
        scales, scales_b = self._step_scales(lr_scale, lr_scale_bias,
                                             idx.shape[0])
        accum = self.accum_steps
        acc = None
        n_steps = idx.shape[0]
        for step_i, (x, t) in enumerate(pf):
            ls = jnp.float32(scales[step_i])
            lsb = jnp.float32(scales_b[step_i])
            rows = jnp.asarray(idx[step_i], jnp.int32)
            if accum == 1:
                self.params, self.vels, m = self._step_fn(
                    self.params, self.vels, x, t,
                    jnp.asarray(mask[step_i]), ep,
                    jnp.uint32(ctrs[step_i]), ls, lsb, rows)
            else:
                grads, m = self._grad_fn(self.params, x, t,
                                         jnp.asarray(mask[step_i]), ep,
                                         jnp.uint32(ctrs[step_i]), rows)
                # a group's first grads ARE the accumulator (right
                # structure, dtype and sharding — no zeros round-trip)
                acc = grads if acc is None \
                    else self._acc_add_fn(acc, grads)
                if (step_i + 1) % accum == 0 or step_i + 1 == n_steps:
                    self.params, self.vels = self._apply_fn(
                        self.params, self.vels, acc, ls, lsb)
                    acc = None
            losses.append(m["loss"])
            n_errs.append(m["n_err"])
            if self.step_callback is not None:
                self.step_callback(epoch, step_i)
        ms = {"loss": jnp.stack(losses), "n_err": jnp.stack(n_errs)}
        return {k: np.asarray(v) for k, v in ms.items()} if sync else ms

    def eval_epoch(self, data, target, indices, batch: int,
                   sync: bool = True) -> dict:
        if self._eval_fn is None:
            self._build_steps()
        idx, mask, _ = self._idx_matrix(np.asarray(indices), batch)
        pf = BatchPrefetcher(self.loader, idx, depth=self.prefetch_depth,
                             device_put=self._device_put,
                             skip_labels=self._x_is_target,
                             raw=self.device_augment)
        losses, n_errs = [], []
        for step_i, (x, t) in enumerate(pf):
            m = self._eval_fn(self.params, x, t,
                              jnp.asarray(mask[step_i]),
                              jnp.asarray(idx[step_i], jnp.int32))
            losses.append(m["loss"])
            n_errs.append(m["n_err"])
        ms = {"loss": jnp.stack(losses), "n_err": jnp.stack(n_errs)}
        return {k: np.asarray(v) for k, v in ms.items()} if sync else ms
