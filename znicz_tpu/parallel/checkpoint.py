"""Orbax checkpoints of the fused trainer's device pytrees.

Parity/extension target: SURVEY.md §5 checkpoint/resume names
"Orbax-style (or hand-rolled) pytree checkpoints" as the TPU
equivalent of the reference Snapshotter.  The hand-rolled tier exists
(``znicz_tpu/snapshotter.py``: host-side .npz of unit Vectors, CLI
resume); this module is the TPU-native tier on top of it — it
checkpoints the *live device state* of a :class:`FusedTrainer`:

* **sharding-aware**: mesh-sharded params/velocities save without a
  host gather round-trip through unit Vectors, and restore back onto
  the trainer's shardings (multi-host: each process writes/reads its
  own shards, Orbax's OCDBT layout);
* **async-capable**: ``save(..., block=False)`` returns while device→
  disk IO proceeds in the background — the standard TPU recipe for
  snapshotting without stalling the step loop.

The spec fingerprint is stored alongside the arrays and checked on
restore, so a checkpoint can't silently load into a different model.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax

from .. import durability


def _spec_fingerprint(spec) -> str:
    return json.dumps(dataclasses.asdict(spec), sort_keys=True,
                      default=str)


def _state(trainer) -> dict:
    return {"params": trainer.params, "vels": trainer.vels}


class TrainerCheckpointer:
    """Save/restore a FusedTrainer's (params, vels) via Orbax.

    ``directory`` holds numbered step checkpoints
    (``<directory>/<step>/``) — keep N with ``max_to_keep``.

    ``on_blessed(step, step_dir)`` fires right after a step's
    durability manifest commits (process 0 only — the manifest owner):
    the step is now *blessed* — verified-restorable by anyone scanning
    the directory — which is exactly the moment a promotion watcher
    (``znicz_tpu.promotion.CheckpointSource``) wants to hear about it
    without polling.  Callback failures are logged, never raised: a
    broken subscriber must not fail the save."""

    def __init__(self, directory: str, max_to_keep: int | None = 3,
                 on_blessed=None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.on_blessed = on_blessed
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))
        #: steps saved async whose manifest write waits on the IO
        self._pending_manifests: set[int] = set()

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def _commit_manifests(self) -> None:
        """Write per-blob sha256 manifests for every finished async
        save (call only after ``wait_until_finished`` — hashing an
        in-flight Orbax write would bless half a checkpoint).  Process
        0 writes, same ownership rule as CheckpointRecovery."""
        pending, self._pending_manifests = self._pending_manifests, set()
        for step in sorted(pending):
            if jax.process_index() == 0 \
                    and os.path.isdir(self._step_dir(step)):
                durability.write_manifest(self._step_dir(step),
                                          kind="checkpoint")
                if self.on_blessed is not None:
                    try:
                        self.on_blessed(step, self._step_dir(step))
                    except Exception:
                        import logging
                        logging.getLogger("TrainerCheckpointer") \
                            .exception("on_blessed callback failed "
                                       "for step %d", step)

    # -- write -------------------------------------------------------------
    def save(self, trainer, step: int, block: bool = True) -> None:
        """Checkpoint the live device state at ``step``; ``block=False``
        lets device→disk IO overlap subsequent training steps (the
        manifest then lands at the next ``wait()``/``save(block=True)``/
        ``close()`` — a manifest must only ever describe bytes that
        finished writing)."""
        ocp = self._ocp
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(_state(trainer)),
                meta=ocp.args.JsonSave(
                    {"spec": _spec_fingerprint(trainer.spec)})))
        self._pending_manifests.add(step)
        if block:
            self._mngr.wait_until_finished()
            self._commit_manifests()

    def wait(self) -> None:
        self._mngr.wait_until_finished()
        self._commit_manifests()

    # -- read --------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def latest_verified_step(self) -> int | None:
        """Newest step whose directory passes
        :func:`durability.verify` — corrupt steps are quarantined
        (renamed ``<step>.corrupt``, which Orbax's integer-named step
        listing then ignores) and the scan falls back to the
        next-newest, the same last-good contract as snapshot resume.
        Steps that predate manifests verify as legacy (existence
        only).  Quarantine/heal writes are process 0's job — the same
        ownership rule as the save-side manifests; other processes
        verify read-only and land on the same answer (they skip the
        same corrupt steps)."""
        try:
            steps = sorted(self._mngr.all_steps(read=True), reverse=True)
        except TypeError:                  # older orbax: no read kwarg
            steps = sorted(self._mngr.all_steps(), reverse=True)
        owner = jax.process_index() == 0
        found = durability.newest_verified(
            (self._step_dir(s) for s in steps),
            on_corrupt="quarantine" if owner else "skip", heal=owner)
        return int(os.path.basename(found)) if found is not None \
            else None

    def restore(self, trainer, step: int | None = None) -> int:
        """Restore into ``trainer`` (in place), re-applying its current
        shardings; returns the restored step.  With ``step=None`` the
        newest *verified* step is restored (corrupt ones quarantined
        and skipped — see :meth:`latest_verified_step`); an explicitly
        requested step is verified first and raises
        :class:`durability.ArtifactCorrupt` rather than feeding Orbax
        rotten bytes."""
        ocp = self._ocp
        if step is None:
            step = self.latest_verified_step()
            if step is None:
                raise FileNotFoundError(
                    f"no verifiable checkpoints under {self.directory}")
        else:
            durability.verify_or_heal(self._step_dir(step),
                                      heal=jax.process_index() == 0)
        # check the spec fingerprint BEFORE touching the arrays: a
        # different model must fail with this message, not with an
        # opaque Orbax tree/shape mismatch from the state restore
        meta = self._mngr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )["meta"]
        want = _spec_fingerprint(trainer.spec)
        if meta["spec"] != want:
            raise ValueError(
                "checkpoint spec mismatch: the saved model differs from "
                "the trainer restoring it (layer kinds/dtypes/hypers)")
        # abstract target carrying each leaf's shape/dtype/sharding —
        # orbax lands restored arrays directly on those shardings
        abstract = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding)
            if isinstance(a, jax.Array) else a,
            _state(trainer))
        state = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract)))["state"]
        trainer.params = state["params"]
        trainer.vels = state["vels"]
        return int(step)

    def close(self) -> None:
        self._mngr.close()          # waits for in-flight writes
        self._commit_manifests()


def save_trainer(trainer, directory: str, step: int = 0,
                 block: bool = True) -> None:
    """One-shot convenience save (no manager lifecycle)."""
    ck = TrainerCheckpointer(directory, max_to_keep=None)
    try:
        ck.save(trainer, step, block=block)
    finally:
        ck.close()          # close() waits for any in-flight write


def restore_trainer(trainer, directory: str, step: int | None = None
                    ) -> int:
    """One-shot convenience restore; returns the restored step."""
    ck = TrainerCheckpointer(directory)
    try:
        return ck.restore(trainer, step)
    finally:
        ck.close()
