"""Fused SOM training: whole epochs as one jitted ``lax.scan``.

The TPU hot path for the Kohonen units (same design as ``parallel.fused``
for the gradient chain): the dataset stays HBM-resident, an epoch's
minibatch index matrix drives a ``lax.scan`` whose body is the
distance→argmin→neighborhood-pull step from ``ops.kohonen``, and the host
syncs once per epoch.  σ/lr schedules are per-epoch scalars passed in, so
recompilation never happens across epochs."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import kohonen as som_ops


class FusedSOMTrainer:
    """Device-resident SOM weights + a compiled epoch function."""

    def __init__(self, weights: np.ndarray, grid_shape: tuple[int, int],
                 workflow=None):
        self.grid_shape = grid_shape
        self.weights = jnp.asarray(weights)
        self._coords = jnp.asarray(som_ops.grid_coords(*grid_shape))
        self.workflow = workflow
        self._epoch_fn = None

    def _build(self):
        coords = self._coords

        def epoch(w, data, idx, lr, sigma):
            def body(w, step_idx):
                x = jnp.take(data, step_idx, axis=0)
                x = x.reshape(len(x), -1)
                win, _ = som_ops.forward_winners(x, w)
                w, diff = som_ops.som_update(w, x, win, coords, lr,
                                             sigma, jnp)
                return w, diff
            return jax.lax.scan(body, w, idx)

        self._epoch_fn = jax.jit(epoch, donate_argnums=(0,))

    def train_epoch(self, data, indices: np.ndarray, batch: int,
                    lr: float, sigma: float) -> float:
        """One epoch over ``indices`` (truncated to full batches — the
        scan body needs one static shape); returns mean |Δw|."""
        if self._epoch_fn is None:
            self._build()
        steps = len(indices) // batch
        if steps == 0:
            raise ValueError("fewer samples than one batch")
        idx = np.asarray(indices[:steps * batch], np.int32).reshape(
            steps, batch)
        self.weights, diffs = self._epoch_fn(
            self.weights, data, idx, jnp.float32(lr), jnp.float32(sigma))
        return float(np.asarray(diffs).mean())

    def write_back(self, forward_unit) -> None:
        forward_unit.weights.mem = np.asarray(self.weights)
