"""Elastic multi-process training via supervised coordinated restart.

Parity target: the reference's master/slave elasticity (SURVEY.md §5
failure row) — slaves could drop off and REJOIN mid-training, receiving
the current weights over the wire from the Twisted master.

TPU-native redesign: under SPMD there is no wire protocol to rejoin
through — `jax.distributed` fixes the process set at initialization,
and that is the right trade (collectives ride ICI with zero
coordination overhead in the hot loop).  Elasticity therefore lives
ABOVE the job: this supervisor launches the fleet, watches it, and on
any member's death restarts ALL processes on a fresh coordinator port;
workers resume from the newest *verified* checkpoint
(`CheckpointRecovery` / `Snapshotter`, crash-safe and
resume-bit-exact — see tests/test_failure_recovery.py; a checkpoint
the dying fleet tore or rotted is quarantined and the scan falls back
to the previous verified one, znicz_tpu.durability — so a corrupt
artifact can never wedge the restart loop).  A replacement worker
"receives current weights" by loading the checkpoint — the same
contract the reference implemented over the wire, at checkpoint rather
than packet granularity.

Scope: SINGLE-HOST multi-process supervision (the supervisor Popens
every worker locally against a loopback coordinator).  On a multi-host
pod, run the fleet under the pod scheduler's restart policy and give
workers the same resume-from-newest-checkpoint contract — the
restart-all-from-checkpoint recovery itself is host-count-agnostic
(see docs/distributed.md).  The 2-process kill/restart scenario is
exercised end-to-end in tests/test_elastic.py.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import time

from ..logger import Logger
from ..resilience.retry import RetryPolicy
from ..telemetry.registry import REGISTRY

_restarts = REGISTRY.counter(
    "elastic_restarts_total",
    "full-fleet coordinated restarts performed by ElasticRunner")
_failures = REGISTRY.counter(
    "elastic_failures_total",
    "fleet rounds that died, by kind (crash | timeout)")
_backoff_s = REGISTRY.counter(
    "elastic_backoff_seconds_total",
    "seconds spent sleeping between fleet restarts")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ElasticRunner(Logger):
    """Launch ``num_processes`` workers; coordinated-restart on death.

    ``make_argv(coordinator, process_id, num_processes)`` returns the
    argv for one worker.  Workers are expected to (a) bootstrap through
    ``parallel.distributed.initialize`` with those coordinates, (b)
    checkpoint at their own granularity, (c) resume from the newest
    checkpoint when one exists, and (d) exit 0 when training completes.

    The supervisor restarts the WHOLE fleet when any member exits
    nonzero, or when a round exceeds ``round_timeout`` (the stall
    guard — OFF unless set: a hung collective can only be detected by
    a deadline the caller chooses) — partial fleets cannot make
    progress under SPMD, and a full restart from the last checkpoint
    is the coordination-free equivalent of the reference's per-slave
    rejoin.

    Worker stdout/stderr stream to per-worker files under ``log_dir``
    (a pipe would deadlock a chatty worker once the OS buffer fills —
    real runs emit plenty of JAX/XLA output).

    Restart pacing: a dead fleet restarts after a bounded-exponential
    jittered backoff (``backoff_base_s * 2**n`` capped at
    ``backoff_max_s`` — a hot restart loop against a dead relay/DCN
    just burns the restart budget in seconds), and
    ``crash_loop_threshold`` failures inside ``crash_loop_window_s``
    fail FAST with every worker's log tail aggregated — a
    deterministic crash (bad config, OOM-on-init) should page the
    operator, not exhaust ``max_restarts`` slowly.  ``status()``
    exposes restarts + the structured last failure for callers."""

    def __init__(self, make_argv, num_processes: int,
                 max_restarts: int = 5, round_timeout: float | None = None,
                 env: dict | None = None, poll_interval: float = 0.2,
                 log_dir: str | None = None,
                 backoff_base_s: float = 0.5, backoff_max_s: float = 15.0,
                 crash_loop_threshold: int = 3,
                 crash_loop_window_s: float = 30.0, sleep_fn=time.sleep):
        super().__init__()
        self.make_argv = make_argv
        self.num_processes = int(num_processes)
        self.max_restarts = int(max_restarts)
        self.round_timeout = round_timeout
        self.env = env
        self.poll_interval = poll_interval
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="elastic_")
        #: restarts actually performed (observable for tests/metrics)
        self.restarts = 0
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.crash_loop_threshold = int(crash_loop_threshold)
        self.crash_loop_window_s = float(crash_loop_window_s)
        self._sleep = sleep_fn
        # ONE backoff implementation repo-wide: the restart schedule is
        # resilience.RetryPolicy's capped-exponential-with-jitter curve
        self._backoff = RetryPolicy(
            max_attempts=max(2, self.max_restarts + 1),
            base_delay_s=self.backoff_base_s,
            max_delay_s=self.backoff_max_s, jitter=0.5, seed=0xE1A5)
        #: structured failure records, newest last (bounded)
        self.failures: list[dict] = []
        self.last_failure: dict | None = None
        self._state = "idle"

    # -- one fleet round ---------------------------------------------------
    def _log_path(self, pid: int) -> str:
        return os.path.join(self.log_dir,
                            f"worker{pid}.round{self.restarts}.log")

    def _launch(self) -> list[subprocess.Popen]:
        coord = f"127.0.0.1:{free_port()}"
        os.makedirs(self.log_dir, exist_ok=True)
        procs = []
        for pid in range(self.num_processes):
            argv = self.make_argv(coord, pid, self.num_processes)
            with open(self._log_path(pid), "w") as log:
                procs.append(subprocess.Popen(
                    [str(a) for a in argv], env=self.env,
                    stdout=log, stderr=subprocess.STDOUT))
        self.info("fleet up: %d workers on %s (logs: %s)", len(procs),
                  coord, self.log_dir)
        return procs

    @staticmethod
    def _reap(procs) -> None:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass

    def _log_tail(self, pid: int, nbytes: int = 400) -> str:
        try:
            with open(self._log_path(pid), "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - nbytes))
                return f.read().decode("utf-8", "replace").strip()
        except OSError:
            return "<no log>"

    def _record_failure(self, kind: str, workers: list[dict]) -> None:
        rec = {"kind": kind, "round": self.restarts,
               "at": time.time(), "monotonic": time.monotonic(),
               "workers": workers}
        self.failures.append(rec)
        del self.failures[:-20]            # bound the history
        self.last_failure = rec
        _failures.inc(kind=kind)

    def _watch(self, procs) -> bool:
        """True = every worker exited 0 (training complete); False =
        somebody died or timed out (caller restarts the fleet).  EVERY
        non-zero exit gets its tail logged and recorded — under SPMD
        the first death is usually a symptom (peer lost a collective),
        and the root cause is in one of the OTHER tails."""
        deadline = (time.monotonic() + self.round_timeout
                    if self.round_timeout else None)
        while True:
            codes = [p.poll() for p in procs]
            if all(c == 0 for c in codes):
                return True
            dead = [(i, c) for i, c in enumerate(codes)
                    if c not in (None, 0)]
            if dead:
                # co-dying workers get a short grace to exit on their
                # own before the reap: under SPMD the first observed
                # death is usually a symptom, and a sibling's OWN exit
                # code + tail beats the -SIGKILL the reap would stamp
                # on it milliseconds later (also de-flakes the
                # both-die-instantly case: a worker still in
                # interpreter startup at the poll gets to finish
                # crashing)
                grace = time.monotonic() + max(self.poll_interval, 1.0)
                while (any(p.poll() is None for p in procs)
                       and time.monotonic() < grace):
                    time.sleep(min(0.05, self.poll_interval))
                dead = [(i, p.poll()) for i, p in enumerate(procs)
                        if p.poll() not in (None, 0)]
                # record only exits observed BEFORE the reap: workers
                # the supervisor kills below are victims, and their
                # -SIGKILL codes would bury the real tails
                workers = []
                for i, c in dead:
                    tail = self._log_tail(i)[-300:]
                    self.warning("worker %d died rc=%s: %s", i, c, tail)
                    workers.append({"process": i, "returncode": c,
                                    "log_tail": tail,
                                    "log": self._log_path(i)})
                self._reap(procs)
                self._record_failure("crash", workers)
                return False
            if deadline is not None and time.monotonic() > deadline:
                self.warning("fleet round timed out after %.0fs",
                             self.round_timeout)
                # snapshot BEFORE the reap: returncode None = "still
                # running at the deadline", which is the truth — the
                # kill signals the reap is about to deliver are the
                # supervisor's doing, not the workers' failure mode
                workers = [{"process": i, "returncode": p.poll(),
                            "log_tail": self._log_tail(i)[-300:],
                            "log": self._log_path(i)}
                           for i, p in enumerate(procs)]
                self._reap(procs)
                self._record_failure("timeout", workers)
                return False
            time.sleep(self.poll_interval)

    def backoff_s(self, restart_index: int) -> float:
        """Jittered, capped delay before restart ``restart_index``
        (1-based) — full-value sleeps would synchronize a multi-fleet
        host into restart storms against the shared coordinator."""
        return self._backoff.backoff_s(restart_index)

    def _aggregate_tails(self, n: int) -> str:
        """Human-readable digest of the last ``n`` failures — the
        fail-fast path must hand the operator every tail at once, not
        a log_dir to spelunk."""
        lines = []
        for rec in self.failures[-n:]:
            for w in rec["workers"]:
                lines.append(f"[round {rec['round']} {rec['kind']} "
                             f"worker {w['process']} "
                             f"rc={w['returncode']}] {w['log_tail']}")
        return "\n".join(lines)

    def _crash_looping(self) -> bool:
        if len(self.failures) < self.crash_loop_threshold:
            return False
        recent = self.failures[-self.crash_loop_threshold:]
        span = recent[-1]["monotonic"] - recent[0]["monotonic"]
        return span <= self.crash_loop_window_s

    # -- public ------------------------------------------------------------
    def status(self) -> dict:
        """Structured supervisor state for callers (CLI, health
        endpoints, tests): restart budget, phase, and the full record
        of the last failure including every dead worker's tail."""
        return {"state": self._state, "restarts": self.restarts,
                "max_restarts": self.max_restarts,
                "num_processes": self.num_processes,
                "failure_count": len(self.failures),
                "last_failure": self.last_failure,
                "log_dir": self.log_dir}

    def run(self) -> int:
        """Supervise until completion.  Returns the restart count;
        raises RuntimeError when ``max_restarts`` is exhausted or a
        crash loop is detected (``crash_loop_threshold`` failures
        within ``crash_loop_window_s``)."""
        while True:
            self._state = "running"
            procs = self._launch()
            try:
                if self._watch(procs):
                    self.info("training complete after %d restart(s)",
                              self.restarts)
                    self._state = "complete"
                    return self.restarts
            finally:
                self._reap(procs)
            if self._crash_looping():
                self._state = "crash_loop"
                raise RuntimeError(
                    f"crash loop: {self.crash_loop_threshold} fleet "
                    f"failures within {self.crash_loop_window_s:.0f}s "
                    f"— failing fast instead of burning the restart "
                    f"budget; last tails:\n"
                    + self._aggregate_tails(self.crash_loop_threshold))
            self.restarts += 1
            _restarts.inc()
            if self.restarts > self.max_restarts:
                self._state = "failed"
                raise RuntimeError(
                    f"fleet failed {self.restarts} times; giving up "
                    f"(max_restarts={self.max_restarts}); last "
                    f"failure tails:\n" + self._aggregate_tails(2))
            delay = self.backoff_s(self.restarts)
            _backoff_s.inc(delay)
            self._state = "backoff"
            self.info("restart %d/%d in %.2fs (%s)", self.restarts,
                      self.max_restarts, delay,
                      self.last_failure["kind"] if self.last_failure
                      else "unknown")
            self._sleep(delay)


def main(argv=None) -> int:
    """CLI: ``python -m znicz_tpu.parallel.elastic -n N [--max-restarts R]
    -- worker.py ARGS...`` — the worker receives
    ``--coordinator HOST:PORT --process-id I --num-processes N``
    appended to its argv."""
    import argparse
    p = argparse.ArgumentParser(
        description="supervised coordinated-restart training fleet")
    p.add_argument("-n", "--num-processes", type=int, required=True)
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--round-timeout", type=float, default=None)
    p.add_argument("--backoff-base-s", type=float, default=0.5)
    p.add_argument("--backoff-max-s", type=float, default=15.0)
    p.add_argument("--crash-loop-threshold", type=int, default=3)
    p.add_argument("--crash-loop-window-s", type=float, default=30.0)
    p.add_argument("worker", nargs=argparse.REMAINDER,
                   help="-- worker.py args...")
    args = p.parse_args(argv)
    worker = list(args.worker)
    if worker and worker[0] == "--":     # only the SEPARATOR; a later
        worker.pop(0)                    # literal -- belongs to the
    if not worker:                       # worker's own argv
        p.error("worker command required after --")

    def make_argv(coord, pid, nproc):
        return [sys.executable, *worker,
                "--coordinator", coord, "--process-id", str(pid),
                "--num-processes", str(nproc)]

    runner = ElasticRunner(make_argv, args.num_processes,
                           max_restarts=args.max_restarts,
                           round_timeout=args.round_timeout,
                           backoff_base_s=args.backoff_base_s,
                           backoff_max_s=args.backoff_max_s,
                           crash_loop_threshold=args.crash_loop_threshold,
                           crash_loop_window_s=args.crash_loop_window_s)
    runner.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
