"""Fleet-scale serving fabric: a router tier over N serve processes.

The cross-process layer of the serving stack (docs/fleet.md):

* :mod:`~znicz_tpu.fleet.router` — ``python -m znicz_tpu route``, a
  thin frontend spreading ``/predict`` over N independent ``serve``
  backends with weighted routing, per-backend circuit breakers
  (ejection/re-admission), transport-failure failover, the PR 10
  deadline/criticality/request-id headers as the wire contract on
  every hop, JSON + binary payload pass-through, and aggregated
  ``/healthz`` · ``/metrics`` (``fleet_*{backend=...}`` families) ·
  ``/statusz`` surfaces.
* :mod:`~znicz_tpu.fleet.rollout` — promote-one-then-fleet:
  :class:`FleetTarget` plugs the fleet into the PR 6 promotion
  controller (canary ONE backend through verify→canary→SLO-watch,
  then walk the rest with weighted traffic splitting,
  generation-skew tolerance, and fleet-wide rollback on a mid-walk
  burn-rate breach).
* :mod:`~znicz_tpu.fleet.placement` — the router decides where
  models live: weighted-rendezvous (cache-affinity) assignment of
  each zoo tenant to a scored subset of backends, residency-/load-
  aware scoring, replication factor, pins, live re-placement via
  ``POST /admin/placement``.
* :mod:`~znicz_tpu.fleet.autoscaler` — elastic fleet:
  ``route --autoscale`` boots and drains real serve processes on the
  SLO burn-rate signal, re-running placement on every membership
  change.
* :mod:`~znicz_tpu.fleet.statestore` — crash-safe control plane:
  ``route --state-dir`` journals every weight / pin / membership /
  child mutation to an fsync'd torn-tail-tolerant JSONL file; a
  restarted router replays its decisions and **reconciles** the
  journaled children (re-adopt alive ones in place, drain half-dead
  or unknown-generation ones, never signal a recycled pid) instead
  of re-booting the fleet.
* :mod:`~znicz_tpu.fleet.ha` — no single point of failure: leased
  router leadership over the state dir (fsync'd epoch-carrying
  lease), hot standbys (``route --standby-of`` / ``--peer``) that
  tail the journal and take over on lease expiry, and split-brain
  **epoch fencing** — a deposed primary refuses its own stale
  mutations and demotes itself instead of double-driving the fleet.

This is the modern rebuild of the paper's VELES master–slave topology
(the Twisted/ZeroMQ master fanning work to slave processes) on
JAX-era serving primitives.
"""

from .router import (Backend, BackendDown, FleetRouter,  # noqa: F401
                     GrayPolicy, parse_backend_spec)
from .rollout import FleetTarget, merge_samples  # noqa: F401
from .placement import (PlacementCandidate,  # noqa: F401
                        PlacementEngine, rank_backends, score_weight)
from .statestore import (ControlPlaneState,  # noqa: F401
                         FencedError, OrphanProcess, StateStore,
                         fold_entry, pid_alive, process_identity)
from .autoscaler import (Autoscaler, ServeLauncher,  # noqa: F401
                         reconcile_children)
from .ha import (HACoordinator, JournalTailer,  # noqa: F401
                 LeaseManager, read_lease, settle_control_plane,
                 write_lease)
