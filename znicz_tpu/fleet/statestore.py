"""Durable fleet control-plane state: journal, replay, reconcile.

The router is the fleet's master (PAPER.md lineage: the VELES master
decides where work lives), and until this module every decision it
made — admin weight overrides, placement pins, membership changes,
and each autoscaler-booted serve process — lived only in router
memory.  A router crash therefore lost all operator intent AND
orphaned real child processes: the classic unprotected-control-plane
failure.  This module gives the control plane the same crash-state
discipline the data plane already has (PR 5's invalidate→blob→
manifest protocol, PR 6's fsync'd promotion ledger):

* :class:`StateStore` — an append-only JSONL journal
  (``<state-dir>/controlplane.jsonl``), fsync'd per record, tolerant
  of exactly one torn final line (crash mid-append): everything
  *before* the tear is durable history, the torn record is dropped
  with a warning, never a crash.  Record kinds: ``weight`` / ``pin``
  / ``rebalance`` (admin mutations), ``join`` / ``leave`` /
  ``ejection`` (membership + breaker audit), ``boot`` / ``adopt`` /
  ``drain`` (autoscaler children, with pid, port, url, boot args and
  a pid-reuse-proof process identity).
* :meth:`StateStore.replay` — folds the stream into
  :class:`ControlPlaneState`: last-write-wins weights and pins, the
  member audit set, and the live children map a restarted autoscaler
  reconciles against (``boot``/``adopt`` adds, ``drain`` removes).
* **Pid-reuse safety** — :func:`process_identity` reads the process
  start time from ``/proc/<pid>/stat`` (field 22: clock ticks since
  boot, immutable for the life of the pid).  A journaled pid whose
  current identity differs is a RECYCLED pid: the child is dead and
  some unrelated process now wears its number — it must be treated
  as dead and never signalled (:class:`OrphanProcess` refuses it).
* :class:`OrphanProcess` — a ``subprocess.Popen``-shaped handle for
  a re-adopted child the restarted router did not spawn (the crash
  reparented it to init, so ``waitpid`` is unavailable): ``poll`` /
  ``send_signal`` / ``terminate`` / ``kill`` / bounded ``wait`` via
  signal-0 liveness polling, every signal gated on the identity
  check above.
* **Epoch fencing** (fleet/ha.py) — when leased leadership is active
  the store carries the holder's ``writer_epoch``: every appended
  record is stamped with it, and a ``fence`` callable (the lease
  file's authoritative epoch) is consulted first.  A newer epoch in
  the lease means this writer was DEPOSED — the append raises
  :class:`FencedError` *before* touching the journal, so a stale
  primary waking from a GC pause can never journal a mutation the
  new primary didn't make.
* **Honest degradation** — :meth:`StateStore.append` is the
  ``statestore.append`` chaos fault site (docs: faults.py table).  A
  failed journal write/fsync (ENOSPC, dying disk) marks the store
  ``degraded`` and propagates the OSError: callers refuse the
  *mutation* (503 + Retry-After) while reads and /predict keep
  serving — a full disk must not silently drop operator intent, and
  must not take the data plane down either.

Families: ``controlplane_journal_records_total{kind}``,
``backend_adopted_total{outcome}`` (reconciliation verdicts, one per
journaled child), ``ha_fenced_mutations_total{action}`` (stale-epoch
writes refused), and the ``controlplane_reconcile_state`` enum gauge
(0 = no journal attached, 1 = replaying/reconciling, 2 = settled) —
docs/observability.md.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal as _signal
import subprocess
import threading
import time

from ..resilience import faults
from ..telemetry.registry import REGISTRY

log = logging.getLogger("fleet")

JOURNAL_NAME = "controlplane.jsonl"

#: controlplane_reconcile_state values (enum gauge)
RECONCILE_OFF = 0          # router runs without a state dir
RECONCILE_RECONCILING = 1  # journal replayed, children being re-probed
RECONCILE_SETTLED = 2      # reconciliation finished, serving normally

_journal_records = REGISTRY.counter(
    "controlplane_journal_records_total",
    "control-plane mutations durably journaled (route --state-dir), "
    "by record kind (weight | pin | rebalance | join | leave | "
    "ejection | boot | adopt | drain)")
_backend_adopted = REGISTRY.counter(
    "backend_adopted_total",
    "journaled autoscaler children a restarted router reconciled, by "
    "verdict (adopted = re-entered rotation in place | dead = pid "
    "gone | stale_pid = pid recycled by an unrelated process, never "
    "signalled | stale_args = unknown boot generation, drained | "
    "replaced = alive but failed healthz/predict canary, drained | "
    "invalid = unusable journal record)")
_reconcile_g = REGISTRY.gauge(
    "controlplane_reconcile_state",
    "restart-reconciliation state of the fleet control plane (0 = no "
    "state dir attached, 1 = journal replayed and children being "
    "re-probed — /predict answers 503 + Retry-After, 2 = settled)")
_fenced_mutations = REGISTRY.counter(
    "ha_fenced_mutations_total",
    "control-plane mutations refused by epoch fencing, by action "
    "(journal record kind, or boot | drain for autoscaler actions "
    "stopped before spawning/signalling): a deposed primary tried to "
    "write with a stale leadership epoch")


def set_reconcile_state(state: int) -> None:
    _reconcile_g.set(float(state))


class FencedError(RuntimeError):
    """A control-plane mutation was refused because the lease file
    carries a newer leadership epoch than this writer holds: this
    process was deposed (GC pause, partition, operator takeover) and
    must demote itself instead of writing.  Deliberately NOT an
    OSError — durability problems degrade, fencing *deposes*."""

    def __init__(self, action: str, writer_epoch: int,
                 authoritative_epoch: int):
        super().__init__(
            f"{action}: writer epoch {writer_epoch} fenced by "
            f"authoritative epoch {authoritative_epoch} — this "
            f"process is no longer the primary")
        self.action = action
        self.writer_epoch = writer_epoch
        self.authoritative_epoch = authoritative_epoch


def process_identity(pid: int) -> str | None:
    """A pid-reuse-proof identity for a live process: the kernel's
    start time in clock ticks since boot (``/proc/<pid>/stat`` field
    22), constant for the pid's whole life and different for any
    later process recycling the number.  None when unreadable (no
    procfs, process gone) — callers must treat None as *unverifiable*,
    not as a match."""
    try:
        with open(f"/proc/{int(pid)}/stat", "rb") as fh:
            stat = fh.read().decode("ascii", "replace")
    except OSError:
        return None
    # the command field (2) is parenthesized and may itself contain
    # spaces/parens — split AFTER its closing paren, not on spaces
    _, _, tail = stat.rpartition(")")
    fields = tail.split()
    if len(fields) < 20:
        return None
    return fields[19]                      # field 22, 1-indexed


def pid_alive(pid: int) -> bool:
    """Signal-0 liveness: True while a process wears this pid (even
    one we may not signal — EPERM proves existence)."""
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class OrphanProcess:
    """Popen-shaped handle for a journaled child this process did not
    spawn.  Every signal is identity-gated: if the recorded identity
    no longer matches the live pid, the number was recycled by an
    unrelated process and we must neither signal nor count it."""

    def __init__(self, pid: int, identity: str | None = None):
        self.pid = int(pid)
        self.identity = identity
        self.returncode: int | None = None

    def _mine(self) -> bool:
        if not pid_alive(self.pid):
            return False
        if self.identity is None:
            return True                    # unverifiable: assume ours
        return process_identity(self.pid) == self.identity

    def poll(self) -> int | None:
        """None while the recorded child is alive; -1 once it is gone
        (or its pid was recycled — same thing for our bookkeeping)."""
        if self.returncode is not None:
            return self.returncode
        if self._mine():
            return None
        self.returncode = -1
        return self.returncode

    def send_signal(self, sig: int) -> None:
        if self.poll() is not None:
            return
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            self.returncode = -1

    def terminate(self) -> None:
        self.send_signal(_signal.SIGTERM)

    def kill(self) -> None:
        self.send_signal(_signal.SIGKILL)

    def wait(self, timeout: float) -> int:
        """Bounded reap-by-polling (the crash reparented the child to
        init, so a real ``waitpid`` is not ours to call).  Raises
        :class:`subprocess.TimeoutExpired` like Popen does."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            rc = self.poll()
            if rc is not None:
                return rc
            time.sleep(0.05)
        rc = self.poll()
        if rc is not None:
            return rc
        raise subprocess.TimeoutExpired(f"pid {self.pid}", timeout)


@dataclasses.dataclass
class ControlPlaneState:
    """What a restarted router recovers from the journal."""

    #: last-write-wins admin weight overrides, by backend name
    weights: dict = dataclasses.field(default_factory=dict)
    #: last-write-wins placement pins, {model: [backend names]};
    #: a cleared pin (backends null) removes the entry
    pins: dict = dataclasses.field(default_factory=dict)
    #: membership audit: joined-minus-left backend names → url
    members: dict = dataclasses.field(default_factory=dict)
    #: live autoscaler children: name → latest boot/adopt record
    #: (pid, port, url, args, identity), minus drained ones
    children: dict = dataclasses.field(default_factory=dict)
    #: highest leadership epoch journaled (fleet/ha.py ``lease``
    #: records; 0 before HA ever ran)
    epoch: int = 0
    #: parseable records folded (torn/junk lines excluded)
    records: int = 0


def fold_entry(st: ControlPlaneState, entry: dict) -> None:
    """Fold ONE journal record into the state: weights and pins are
    last-write-wins; ``boot``/``adopt`` add (or refresh) a child,
    ``drain`` and ``leave`` remove it; ``lease`` advances the epoch
    high-water mark; ``ejection``/``rebalance`` and unknown kinds are
    audit-only.  Shared by :meth:`StateStore.replay` and the HA
    standby's incremental journal tailer (fleet/ha.py) so warm state
    and restart state can never fold differently."""
    kind = entry.get("kind")
    name = entry.get("backend")
    if kind == "weight" and name:
        try:
            st.weights[str(name)] = float(entry.get("weight"))
        except (TypeError, ValueError):
            pass
    elif kind == "pin":
        model = entry.get("model")
        if not model:
            return
        pin = entry.get("backends")
        if pin:
            st.pins[str(model)] = [str(n) for n in pin]
        else:
            st.pins.pop(str(model), None)
    elif kind == "join" and name:
        st.members[str(name)] = entry.get("url")
    elif kind == "leave" and name:
        st.members.pop(str(name), None)
        st.children.pop(str(name), None)
    elif kind in ("boot", "adopt") and name:
        st.children[str(name)] = {
            "pid": entry.get("pid"),
            "port": entry.get("port"),
            "url": entry.get("url"),
            "args": entry.get("args") or [],
            "identity": entry.get("identity")}
    elif kind == "drain" and name:
        st.children.pop(str(name), None)
    elif kind == "lease":
        try:
            st.epoch = max(st.epoch, int(entry.get("epoch", 0)))
        except (TypeError, ValueError):
            pass


class StateStore:
    """Append/replay over one fsync'd JSONL journal (the
    ``promotion/ledger.py`` idiom, holding control-plane mutations
    instead of promotion outcomes).  A missing file is an empty
    history; the directory is created on first append."""

    def __init__(self, state_dir: str):
        self.state_dir = os.fspath(state_dir)
        self.path = os.path.join(self.state_dir, JOURNAL_NAME)
        self._lock = threading.Lock()
        #: leadership epoch stamped on every append; None = HA off
        #: (plain PR 17 operation, records carry no epoch)
        self.writer_epoch: int | None = None
        #: zero-arg callable returning the authoritative epoch (the
        #: lease file); consulted before every stamped append
        self._fence = None
        #: True after a failed journal write (ENOSPC, dying disk) —
        #: the control plane is refusing mutations but still serving
        self.degraded = False

    def set_writer_epoch(self, epoch: int | None,
                         fence=None) -> None:
        """Arm (or disarm, epoch None) epoch fencing.  ``fence`` is a
        zero-arg callable returning the authoritative epoch — in
        production the HA coordinator passes the lease-file reader,
        so a deposed writer discovers its deposition on its very next
        mutation, not on some later tick."""
        self.writer_epoch = int(epoch) if epoch is not None else None
        self._fence = fence if epoch is not None else None

    def authoritative_epoch(self) -> int | None:
        """What the fence says right now (None when unfenced)."""
        if self._fence is None:
            return None
        try:
            return int(self._fence())
        except Exception:
            # an unreadable lease must not wedge the primary: treat
            # as "no newer epoch observed"
            return None

    def fenced(self) -> bool:
        """True when the authoritative epoch has moved past ours —
        every mutation path (append, autoscaler boot/drain) checks
        this before acting."""
        if self.writer_epoch is None:
            return False
        auth = self.authoritative_epoch()
        return auth is not None and auth > self.writer_epoch

    def _check_fence(self, action: str) -> None:
        if self.writer_epoch is None:
            return
        auth = self.authoritative_epoch()
        if auth is not None and auth > self.writer_epoch:
            _fenced_mutations.inc(action=str(action))
            raise FencedError(str(action), self.writer_epoch, auth)

    def append(self, kind: str, **fields) -> dict:
        """Durably journal one mutation (``{"ts", "kind", ...}``).
        fsync per record: control-plane mutations are rare and each
        one is exactly what a post-crash replay needs.

        With fencing armed the record is stamped with ``epoch`` and
        the fence is checked FIRST — a deposed writer raises
        :class:`FencedError` without touching the journal.  A write
        failure (the ``statestore.append`` chaos fault site) marks
        the store ``degraded`` and re-raises the OSError: the caller
        refuses the mutation honestly instead of pretending it was
        durable."""
        self._check_fence(kind)
        entry = {"ts": time.time(), "kind": kind, **fields}
        if self.writer_epoch is not None:
            entry["epoch"] = self.writer_epoch
        line = json.dumps(entry, sort_keys=True, default=str) + "\n"
        try:
            faults.inject("statestore.append")
            with self._lock:
                os.makedirs(self.state_dir, exist_ok=True)
                with open(self.path, "a") as fh:
                    fh.write(line)
                    fh.flush()
                    os.fsync(fh.fileno())
        except OSError:
            self.degraded = True
            raise
        self.degraded = False
        _journal_records.inc(kind=str(kind))
        return entry

    def entries(self) -> list:
        """Every parseable record, oldest first.  A torn FINAL line
        (crash mid-append) is skipped with a warning; a torn line
        anywhere else is corruption worth the same warning but never
        a crash — refusing to restart the router over one bad line
        would turn bookkeeping into an outage."""
        try:
            with open(self.path) as fh:
                lines = fh.read().splitlines()
        except FileNotFoundError:
            return []
        out = []
        for i, line in enumerate(lines, start=1):
            if not line.strip():
                continue
            try:
                entry = json.loads(line)
                if not isinstance(entry, dict):
                    raise ValueError("not an object")
            except ValueError:
                log.warning("%s:%d: skipping torn/unparseable "
                            "journal record", self.path, i)
                continue
            out.append(entry)
        return out

    def replay(self) -> ControlPlaneState:
        """Fold the journal into restart state via
        :func:`fold_entry`: weights and pins are last-write-wins;
        ``boot``/``adopt`` add (or refresh) a child, ``drain`` and
        ``leave`` remove it; ``lease`` advances the epoch; unknown
        kinds are audit-only."""
        st = ControlPlaneState()
        for entry in self.entries():
            st.records += 1
            fold_entry(st, entry)
        return st

    def status(self) -> dict:
        st = self.replay()
        return {"path": self.path, "records": st.records,
                "children": sorted(st.children),
                "weights": st.weights,
                "pins": {m: list(v) for m, v in st.pins.items()},
                "epoch": st.epoch, "degraded": self.degraded}
