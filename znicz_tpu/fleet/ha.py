"""Highly-available fleet front: leased leadership, hot standby,
split-brain fencing.

PR 17 made the control plane crash-safe *below* the router — but the
router itself stayed a single process, and the paper's master–slave
topology (PAPER.md ``apply_data_from_slave`` lineage) always assumed
exactly one live master.  This module finishes that story: the master
role survives the master's death, and two masters can never both
drive the autoscaler or the admin state.

* **Leased leadership** — one fsync'd, atomically-renamed lease
  record (``<state-dir>/lease.json``) carries a monotonically
  increasing **epoch** plus the PR 17 pid + kernel-start-time process
  identity (:func:`~znicz_tpu.fleet.statestore.process_identity`).
  The primary re-writes ``renewed_ts`` on a tick; a lease whose
  holder is provably dead (pid gone, or the identity says the pid was
  recycled) is acquirable immediately — no TTL wait for a clean
  crash on the same host.
* **Hot standby** — ``route --standby-of URL`` (or the symmetric
  ``--peer URL``) runs a full router process that answers
  ``/healthz``/``/metrics`` but refuses ``/predict`` and admin
  mutations with 503 + ``Retry-After`` (the 200-or-503 contract —
  a standby is *honestly not serving*, never half-serving).  Its
  :class:`JournalTailer` follows ``controlplane.jsonl`` so weights,
  pins, members and the live-children map are warm in memory; its
  watch loop probes the primary's ``/healthz`` and the lease file.
  On lease expiry it acquires the lease, **bumps the epoch**, adopts
  the journal's live children in place (PR 17
  :class:`~znicz_tpu.fleet.statestore.OrphanProcess` — zero
  double-boots), replays weights/pins, and starts serving.
* **Epoch fencing — the hard half.**  Every journal mutation is
  stamped with the writer's epoch and *gated* on it
  (:meth:`StateStore.append` raises
  :class:`~znicz_tpu.fleet.statestore.FencedError` when the lease
  shows a newer epoch), and every autoscaler boot/drain re-checks the
  fence before acting.  A deposed primary waking from a GC pause or a
  partition sees the newer epoch, refuses its own pending mutations,
  demotes itself to standby, and never double-boots or double-drains
  a backend.

Families: ``fleet_role``, ``ha_epoch``, ``ha_lease_renewals_total``,
``ha_takeovers_total``, ``ha_demotions_total`` (here) and
``ha_fenced_mutations_total`` (statestore) — docs/observability.md.
The acceptance drill is ``chaos --scenario ha`` / tools/ha_smoke.sh.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request

from ..telemetry.registry import REGISTRY
from .statestore import (ControlPlaneState, fold_entry, pid_alive,
                         process_identity)

log = logging.getLogger("fleet")

LEASE_NAME = "lease.json"

_role_g = REGISTRY.gauge(
    "fleet_role",
    "this router process's high-availability role (1 = primary, "
    "holding the leadership lease and serving /predict; 0 = hot "
    "standby, tailing the journal and refusing traffic with 503 + "
    "Retry-After)")
_epoch_g = REGISTRY.gauge(
    "ha_epoch",
    "the leadership epoch this process holds (primary) or last "
    "observed in the lease file (standby) — strictly increasing "
    "across failovers; journal mutations from older epochs are "
    "fenced")
_renewals = REGISTRY.counter(
    "ha_lease_renewals_total",
    "successful leadership-lease renewals by the primary's renew "
    "tick (a flatlined rate with a live primary is the pre-failover "
    "alarm)")
_takeovers = REGISTRY.counter(
    "ha_takeovers_total",
    "standby promotions: the lease expired (or its holder was "
    "provably dead) and this process acquired it, bumped the epoch "
    "and started serving")
_demotions = REGISTRY.counter(
    "ha_demotions_total",
    "self-demotions by a deposed primary: a renew tick or a fenced "
    "journal mutation revealed a newer epoch, so this process "
    "stopped mutating and fell back to standby")


def lease_path(state_dir: str) -> str:
    return os.path.join(os.fspath(state_dir), LEASE_NAME)


def read_lease(state_dir: str) -> dict | None:
    """The current lease record, or None when absent/unreadable.
    Writes are atomic renames, so a torn read is impossible; junk is
    treated as no-lease (acquirable) rather than a crash."""
    try:
        with open(lease_path(state_dir)) as fh:
            raw = fh.read()
    except OSError:
        return None
    try:
        obj = json.loads(raw)
    except ValueError:
        log.warning("%s: unparseable lease record — treating as "
                    "absent", lease_path(state_dir))
        return None
    return obj if isinstance(obj, dict) else None


def write_lease(state_dir: str, record: dict) -> None:
    """Atomically publish one lease record: write-temp, fsync,
    rename, fsync the directory — the PR 5 invalidate→blob→manifest
    durability discipline, sized down to one file."""
    state_dir = os.fspath(state_dir)
    os.makedirs(state_dir, exist_ok=True)
    path = lease_path(state_dir)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(json.dumps(record, sort_keys=True))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    dirfd = os.open(state_dir, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def current_epoch(state_dir: str) -> int:
    """The authoritative epoch: what the lease file says right now
    (0 before any lease exists).  This is the fence every journal
    mutation is gated on."""
    rec = read_lease(state_dir)
    if rec is None:
        return 0
    try:
        return int(rec.get("epoch", 0))
    except (TypeError, ValueError):
        return 0


class LeaseManager:
    """Acquire/renew/step-down over the one lease file.

    Single-writer-per-epoch by construction: acquisition bumps the
    epoch and then re-reads to confirm the atomic rename race was won
    (last writer wins; the loser sees the winner's record and stays
    standby).  ``epoch`` is None while not holding."""

    def __init__(self, state_dir: str, *, holder: str,
                 url: str | None = None, ttl_s: float = 3.0,
                 clock=time.time):
        if float(ttl_s) <= 0:
            raise ValueError(f"lease ttl must be > 0, got {ttl_s!r}")
        self.state_dir = os.fspath(state_dir)
        self.holder = str(holder)
        self.url = url
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self.epoch: int | None = None

    # -- reads -------------------------------------------------------------
    def read(self) -> dict | None:
        return read_lease(self.state_dir)

    def observed_epoch(self) -> int:
        return current_epoch(self.state_dir)

    def expired(self, rec: dict, now: float | None = None) -> bool:
        """True once the record's own TTL has elapsed since its last
        renewal (junk fields read as expired — an unparseable lease
        must be acquirable, not a deadlock)."""
        now = self._clock() if now is None else now
        try:
            renewed = float(rec.get("renewed_ts", 0.0))
            ttl = float(rec.get("ttl_s", self.ttl_s))
        except (TypeError, ValueError):
            return True
        return now - renewed > ttl

    @staticmethod
    def holder_alive(rec: dict) -> bool:
        """Same-host liveness shortcut: the recorded pid must exist
        AND wear the recorded kernel start-time identity.  A dead or
        recycled pid means the holder is gone — the lease is
        acquirable without waiting out the TTL."""
        pid = rec.get("pid")
        if not pid:
            return False
        try:
            pid = int(pid)
        except (TypeError, ValueError):
            return False
        if not pid_alive(pid):
            return False
        recorded = rec.get("identity")
        if recorded is not None \
                and process_identity(pid) != recorded:
            return False
        return True

    def _mine(self, rec: dict) -> bool:
        return (rec.get("pid") == os.getpid()
                and rec.get("holder") == self.holder)

    # -- writes ------------------------------------------------------------
    def acquire(self) -> bool:
        """Try to take leadership: succeeds against no lease, an
        expired lease, a provably-dead holder, or our own record.
        Bumps the epoch (unless re-acquiring our own), publishes, and
        re-reads to confirm the rename race was won."""
        rec = self.read()
        if rec is not None and not self._mine(rec):
            if not self.expired(rec) and self.holder_alive(rec):
                return False
        try:
            held = int(rec.get("epoch", 0)) if rec is not None else 0
        except (TypeError, ValueError):
            held = 0
        epoch = held if (rec is not None and self._mine(rec)
                         and held > 0) else held + 1
        now = self._clock()
        record = {"epoch": epoch, "holder": self.holder,
                  "url": self.url, "pid": os.getpid(),
                  "identity": process_identity(os.getpid()),
                  "acquired_ts": now, "renewed_ts": now,
                  "ttl_s": self.ttl_s}
        try:
            write_lease(self.state_dir, record)
        except OSError as e:
            log.warning("lease acquire failed to publish: %s", e)
            return False
        cur = self.read()
        if cur is not None and self._mine(cur) \
                and cur.get("epoch") == epoch:
            self.epoch = epoch
            return True
        return False                      # lost the rename race

    def renew(self) -> bool:
        """The primary's heartbeat: push ``renewed_ts`` forward.
        Returns False — and drops the held epoch — when the lease is
        no longer ours (a newer epoch exists: we are DEPOSED and must
        not write)."""
        if self.epoch is None:
            return False
        rec = self.read()
        if rec is None or not self._mine(rec):
            self.epoch = None
            return False
        try:
            if int(rec.get("epoch", -1)) != self.epoch:
                self.epoch = None
                return False
        except (TypeError, ValueError):
            self.epoch = None
            return False
        rec["renewed_ts"] = self._clock()
        try:
            write_lease(self.state_dir, rec)
        except OSError as e:
            # a failed renewal is NOT a deposition — the lease still
            # bears our epoch; the next tick retries while the TTL
            # window holds
            log.warning("lease renew failed to publish: %s", e)
            return True
        _renewals.inc()
        return True

    def step_down(self) -> None:
        """Stop holding.  If the lease is still ours, back-date its
        renewal so a peer can take over immediately instead of
        waiting out the TTL (the clean-handoff path)."""
        rec = self.read()
        if rec is not None and self._mine(rec) \
                and rec.get("epoch") == self.epoch:
            rec["renewed_ts"] = (self._clock()
                                 - float(rec.get("ttl_s", self.ttl_s))
                                 - 1.0)
            try:
                write_lease(self.state_dir, rec)
            except OSError:
                pass                     # expiry will release it
        self.epoch = None


class JournalTailer:
    """Incremental follower of ``controlplane.jsonl`` — the standby's
    warm state.  Consumes only complete lines (a torn tail is left
    for the next poll, the same tolerance as
    :meth:`StateStore.entries`), folding each record into one
    :class:`ControlPlaneState` so promotion starts from the journal's
    live weights/pins/members/children without a full re-read."""

    def __init__(self, store):
        self.store = store
        self.state = ControlPlaneState()
        self._offset = 0

    def poll(self) -> int:
        """Fold newly appended complete records; returns how many."""
        try:
            with open(self.store.path, "rb") as fh:
                fh.seek(self._offset)
                chunk = fh.read()
        except OSError:
            return 0
        if not chunk:
            return 0
        end = chunk.rfind(b"\n")
        if end < 0:
            return 0                      # torn tail: wait for more
        folded = 0
        for line in chunk[:end].split(b"\n"):
            if not line.strip():
                continue
            try:
                entry = json.loads(line)
                if not isinstance(entry, dict):
                    raise ValueError("not an object")
            except ValueError:
                continue                  # torn/junk line: skip
            fold_entry(self.state, entry)
            self.state.records += 1
            folded += 1
        self._offset += end + 1
        return folded


class HACoordinator:
    """The role state machine: primary renew tick, standby watch
    loop, promotion and self-demotion.

    Wiring (the route CLI does this): ``attach(router=...,
    promote=..., demote=...)`` then :meth:`try_acquire` (symmetric
    start) and :meth:`start`.  The promote hook adopts children and
    opens the traffic gate; the demote hook closes it and stops the
    autoscaler loop — children are NEVER drained on demotion, they
    belong to the new primary now."""

    def __init__(self, store, *, url: str | None = None,
                 peer_url: str | None = None,
                 holder: str | None = None, ttl_s: float = 3.0,
                 renew_interval_s: float | None = None,
                 probe_timeout_s: float = 2.0):
        self.store = store
        self.lease = LeaseManager(
            store.state_dir,
            holder=holder or f"router-{os.getpid()}",
            url=url, ttl_s=ttl_s)
        self.peer_url = peer_url
        self.ttl_s = float(ttl_s)
        self.renew_interval_s = (float(renew_interval_s)
                                 if renew_interval_s is not None
                                 else max(0.2, self.ttl_s / 3.0))
        self.probe_timeout_s = float(probe_timeout_s)
        self.tailer = JournalTailer(store)
        self._lock = threading.Lock()
        self._role = "standby"
        self._promote_fn = None
        self._demote_fn = None
        self._fenced = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._takeovers = 0
        self._demotions = 0
        self._peer_healthy: bool | None = None
        _role_g.set(0.0)
        _epoch_g.set(float(self.lease.observed_epoch()))

    # -- wiring ------------------------------------------------------------
    def attach(self, router=None, promote=None, demote=None) -> None:
        if promote is not None:
            self._promote_fn = promote
        if demote is not None:
            self._demote_fn = demote
        if router is not None:
            router.attach_ha(self)

    # -- role surface ------------------------------------------------------
    @property
    def role(self) -> str:
        with self._lock:
            return self._role

    @property
    def epoch(self) -> int:
        held = self.lease.epoch
        return held if held is not None else self.lease.observed_epoch()

    def is_primary(self) -> bool:
        return self.role == "primary"

    def primary_url(self) -> str | None:
        """Where traffic should go instead of this standby: the
        lease holder's advertised url, else the configured peer."""
        rec = self.lease.read()
        if rec is not None and rec.get("url") \
                and not self.lease._mine(rec):
            return str(rec["url"])
        return self.peer_url

    def retry_after_s(self) -> int:
        """Honest come-back bound for a standby refusal: one lease
        TTL — by then either the primary answered or this standby
        owns the lease, bounded [1, 30] like the router's."""
        return max(1, min(30, int(self.ttl_s)
                          + (0 if self.ttl_s == int(self.ttl_s)
                             else 1)))

    def status(self) -> dict:
        with self._lock:
            role = self._role
            takeovers, demotions = self._takeovers, self._demotions
            peer_healthy = self._peer_healthy
        out = {"role": role, "epoch": self.epoch,
               "lease_ttl_s": self.ttl_s,
               "takeovers": takeovers, "demotions": demotions}
        if role != "primary":
            out["primary_url"] = self.primary_url()
            if peer_healthy is not None:
                out["primary_healthy"] = peer_healthy
        return out

    # -- transitions -------------------------------------------------------
    def note_fenced(self) -> None:
        """A journal mutation hit :class:`FencedError`: a newer epoch
        owns the fleet.  Callable from any thread (the demotion runs
        on the coordinator thread — never inline, a fenced autoscaler
        tick must not join its own thread)."""
        self._fenced.set()

    def try_acquire(self) -> bool:
        """One acquisition attempt + role flip on success (the
        symmetric ``--peer`` start and the standby's takeover path)."""
        if not self.lease.acquire():
            _epoch_g.set(float(self.lease.observed_epoch()))
            return False
        self._become_primary()
        return True

    def _become_primary(self) -> None:
        with self._lock:
            self._role = "primary"
        self._fenced.clear()
        epoch = self.lease.epoch or 0
        self.store.set_writer_epoch(epoch,
                                    fence=self.lease.observed_epoch)
        _role_g.set(1.0)
        _epoch_g.set(float(epoch))
        try:
            # the epoch bump is itself journaled: replay and the
            # chaos drill read leadership history from the one log
            self.store.append("lease", epoch=epoch,
                              holder=self.lease.holder,
                              url=self.lease.url)
        except OSError as e:
            log.warning("lease journal record not durable: %s", e)
        log.info("ha: primary (epoch %d, holder %s)", epoch,
                 self.lease.holder)

    def _demote(self, reason: str) -> None:
        with self._lock:
            already = self._role == "standby"
            self._role = "standby"
            if not already:
                self._demotions += 1
        if already:
            return
        _demotions.inc()
        _role_g.set(0.0)
        self.store.set_writer_epoch(None)
        self.lease.step_down()
        _epoch_g.set(float(self.lease.observed_epoch()))
        self._fenced.clear()
        log.warning("ha: demoted to standby (%s) — refusing "
                    "mutations, children left to the new primary",
                    reason)
        if self._demote_fn is not None:
            try:
                self._demote_fn()
            except Exception:
                log.exception("ha: demote hook failed")

    def _promote(self) -> None:
        with self._lock:
            self._takeovers += 1
        _takeovers.inc()
        log.warning("ha: lease acquired (epoch %d) — promoting",
                    self.lease.epoch or 0)
        if self._promote_fn is not None:
            try:
                self._promote_fn(self.tailer.state)
            except Exception:
                # a half-failed promotion still holds the lease: the
                # router serves what it adopted; the next renew tick
                # keeps leadership honest
                log.exception("ha: promote hook failed")

    # -- the watch/renew loop ----------------------------------------------
    def probe_peer(self) -> bool | None:
        """One bounded ``/healthz`` probe at the primary (None when
        no peer url is known).  Advisory only: leadership is decided
        by the lease, not the probe — a partition that hides the
        primary's healthz must NOT start a second primary while the
        lease is being renewed."""
        url = self.primary_url()
        if not url:
            return None
        probe = url if url.endswith("/") else url + "/"
        try:
            with urllib.request.urlopen(
                    probe + "healthz",
                    timeout=self.probe_timeout_s) as r:
                ok = r.status == 200
        except Exception:
            ok = False
        with self._lock:
            self._peer_healthy = ok
        return ok

    def step(self) -> str:
        """One tick of the role machine (the loop body, callable
        directly from tests): renew when primary, watch/acquire when
        standby.  Returns the action taken."""
        if self.is_primary():
            if self._fenced.is_set():
                self._demote("fenced journal mutation")
                return "demoted"
            if not self.lease.renew():
                self._demote(f"lease lost to epoch "
                             f"{self.lease.observed_epoch()}")
                return "demoted"
            _epoch_g.set(float(self.lease.epoch or 0))
            return "renewed"
        # standby: keep state warm, watch the primary, take over on
        # an expired/abandoned lease
        self.tailer.poll()
        self.probe_peer()
        rec = self.lease.read()
        _epoch_g.set(float(self.lease.observed_epoch()))
        if rec is not None and not self.lease.expired(rec) \
                and self.lease.holder_alive(rec):
            return "watching"
        if self.try_acquire():
            self.tailer.poll()            # fold the journal's tail
            self._promote()
            return "promoted"
        return "watching"

    def _run(self) -> None:
        while True:
            interval = (self.renew_interval_s if self.is_primary()
                        else max(0.1, self.ttl_s / 4.0))
            if self._stop.wait(interval):
                return
            try:
                self.step()
            except Exception:             # the loop must outlive a tick
                log.exception("ha: coordinator tick failed")

    def start(self) -> "HACoordinator":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="znicz-fleet-ha")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
        if self.is_primary():
            self.lease.step_down()


def settle_control_plane(router, scaler, launcher, store, state, *,
                         reconcile_deadline_s: float = 30.0,
                         min_backends: int = 1) -> dict:
    """Bring a router's control plane to SETTLED from replayed journal
    state: re-adopt journaled children in place (never re-boot a
    survivor), boot only the floor shortfall, replay last-write-wins
    weights and pins, then close the reconcile window.  Shared by the
    route CLI's primary boot and the standby's promotion — both paths
    answer 503 + Retry-After while this runs."""
    outcomes: dict = {}
    if scaler is not None and state.children:
        from .autoscaler import reconcile_children
        outcomes = reconcile_children(
            router, scaler, launcher, state.children,
            deadline_s=reconcile_deadline_s)
        print(f"reconcile: {outcomes}", flush=True)
    elif state.children:
        print(f"reconcile: journal records {len(state.children)} "
              f"children but --autoscale is off — leaving them "
              f"untouched", flush=True)
    if scaler is not None and launcher is not None:
        # the floor covers only what re-adoption missed
        while router.backend_count() < max(1, int(min_backends)):
            b, proc = launcher.spawn(scaler.next_index())
            router.add_backend(b)
            scaler.adopt(b, proc)
            print(f"autoscale: booted floor backend {b.name} at "
                  f"{b.url}", flush=True)
    for nm, w in state.weights.items():
        rb = router.by_name.get(nm)
        if rb is not None:
            try:
                rb.set_weight(w)
            except ValueError:
                pass
    if state.pins and router.placement is not None:
        router.placement.restore_pins(state.pins)
        router.recompute_placement(cause="admin")
    router.end_reconcile()
    print(f"reconcile: settled ({state.records} journal records "
          f"replayed)", flush=True)
    return outcomes
