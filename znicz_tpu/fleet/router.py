"""Fleet router: one thin frontend spreading /predict over N backends.

Everything before this tier lived in ONE process — the zoo, the
overload defenses, the SLO engine, the fast wire path all scale a
single host.  This module is the cross-process tier the "millions of
users" story needs (ROADMAP "Fleet-scale serving fabric"): a router
process (``python -m znicz_tpu route``) fronting N independent
``serve`` processes, the modern rebuild of the paper's VELES
master–slave topology (a Twisted/ZeroMQ master fanning work to slave
processes) on plain HTTP/1.1 keep-alive.

Routing (``POST /predict``):

* **Weighted spread** — smooth weighted round-robin (the nginx
  algorithm: deterministic, no RNG on the request path) over the
  backends whose circuit breaker admits traffic.  Weights are live
  (``POST /admin/weight``) — the rolling-promotion walk
  (:mod:`znicz_tpu.fleet.rollout`) uses them to split traffic toward
  or away from a canarying backend.
* **Per-backend circuit breakers** — PR 2/8's breaker + sick-replica
  ejection lifted to the process boundary: a backend whose forwards
  fail at the transport level trips its breaker and drops out of
  rotation (*ejection*); after the cooldown a single half-open probe
  (live request or the background prober) re-admits it.  A dead
  backend costs its in-flight requests one failover, not an outage.
* **Failover** — a transport-level forward failure (connection
  refused/reset, timeout) retries the SAME request on the next
  healthy backend while the deadline allows; ``/predict`` is
  idempotent by contract, so a request half-served by a killed
  backend re-runs safely.  Only when every candidate is refused does
  the client see a 503 — always with an honest ``Retry-After``
  (the 200-or-503 contract, never a hang, never a raw 500).
* **Wire contract on every hop** — the PR 10 headers travel across
  the router: ``X-Request-Id`` is accepted/generated here and
  forwarded, so one id names the flight records in BOTH processes
  (the router records a ``router.forward`` span per hop);
  ``X-Deadline-Ms`` is re-issued to the backend as the *remaining*
  budget — decremented by the observed hop latency — and a request
  whose budget is already gone answers 504 at this hop instead of
  burning a backend slot; ``X-Criticality`` / ``X-Model`` forward
  unchanged (empty/whitespace values read as unset, the same pins the
  serving front carries).  Bodies pass through as raw bytes — JSON and
  the PR 13 binary tensor format (``application/x-znicz-tensor``)
  route identically, the router never parses a payload.

* **Response memoization** (``--memoize``; the PR 13 serving-tier pin
  lifted one tier) — a repeat request body under the fleet's single
  backend-reported generation answers at the router with NO backend
  hop (``fleet_response_cache_*`` families, ``X-Fleet-Cache: hit``).
  Keyed per generation and bypassed entirely on a mixed-generation
  fleet (mid-roll); a store only lands when the answering backend's
  ``X-Model-Generation`` header confirms the keyed generation, so a
  swap between health probes cannot poison the cache.

Aggregated surfaces: ``GET /healthz`` (fleet verdict + one row per
backend: breaker state, weight, generation, last probe), ``GET
/metrics`` (JSON fleet view; Prometheus text carries the
``fleet_*{backend=...}`` families — docs/observability.md), ``GET
/statusz`` (the human one-pager, docs/fleet.md).

Degradation contract (pinned by ``chaos --scenario fleet``): a killed
backend mid-burst yields zero raw 500s and zero hangs — ejection plus
failover, with ``Retry-After``'d 503s only for genuinely lost
capacity.
"""

from __future__ import annotations

import collections
import dataclasses
import http.client as _http_client
import itertools
import json
import logging
import threading
import time
import urllib.parse

import hashlib

import numpy as np

from . import placement as placement_mod
from . import statestore as statestore_mod
from ..resilience import overload
from ..resilience.breaker import CircuitBreaker
from ..serving import wire as wire_mod
from ..serving.memo import ResponseCache
from ..serving.server import (DeepBacklogHTTPServer, FastHTTPHandler,
                              _json_object, _outcome_of,
                              _tracez_filters)
from ..telemetry import (buildinfo, debugz, flightrecorder, tracestore,
                         tracing)
from ..telemetry.registry import (DEFAULT_LATENCY_BUCKETS_MS,
                                  PROMETHEUS_CONTENT_TYPE, REGISTRY)

#: routes with their own label value in requests_total/errors_total
#: (same bounded-cardinality rule as the serving front)
_ROUTES = ("/predict", "/healthz", "/metrics", "/statusz", "/tracez",
           "/admin/weight", "/admin/placement")

_fleet_requests = REGISTRY.counter(
    "fleet_requests_total",
    "requests the router forwarded to a backend, by backend name and "
    "the HTTP status the backend answered (transport failures are not "
    "counted here — see fleet_failovers_total)")
_fleet_failovers = REGISTRY.counter(
    "fleet_failovers_total",
    "transport-level forward failures (connection refused/reset, "
    "timeout) per backend — each one either failed over to another "
    "backend or became a Retry-After'd 503")
_fleet_forward_hist = REGISTRY.histogram(
    "fleet_forward_latency_ms",
    "router→backend hop wall time (connect-or-reuse + backend answer "
    "+ read), per backend, milliseconds",
    buckets=DEFAULT_LATENCY_BUCKETS_MS)
_fleet_cache_hits = REGISTRY.counter(
    "fleet_response_cache_hits_total",
    "/predict answers served from the ROUTER-tier response "
    "memoization cache — no backend hop at all (route --memoize; "
    "keyed on the fleet's single backend-reported generation, "
    "bypassed on mixed-generation fleets)")
_fleet_cache_misses = REGISTRY.counter(
    "fleet_response_cache_misses_total",
    "router-tier response-cache lookups that went on to a backend "
    "forward (the hit/(hit+miss) ratio is the fabric traffic the "
    "cache absorbs)")
_fleet_cache_bytes = REGISTRY.gauge(
    "fleet_response_cache_bytes",
    "bytes of memoized responses retained at the router tier "
    "(bounded by route --memoize / --memoize-mb, LRU-evicted)")
_fleet_request_hist = REGISTRY.histogram(
    "fleet_request_latency_ms",
    "end-to-end POST /predict wall time AT THE ROUTER (memo hits, "
    "forwards, failovers and refusals all observe) — the e2e signal "
    "the autoscaler's latency-objective burn judges, milliseconds",
    buckets=DEFAULT_LATENCY_BUCKETS_MS)
_gray_demotions = REGISTRY.counter(
    "gray_demotions_total",
    "gray-failure demotion episodes per backend: the differential "
    "prober + forwarded-predict EWMA judged a probe-green backend "
    "predict-sick for the full hysteresis window and began decaying "
    "its effective weight (counted once per episode, not per decay "
    "step)")

log = logging.getLogger("fleet")


@dataclasses.dataclass(frozen=True)
class GrayPolicy:
    """Knobs of the gray-failure detector (docs/fleet.md).

    A *gray* backend answers ``/healthz`` but fails or stalls real
    predicts — the transport breaker never sees a failure, so it
    never ejects.  The detector keeps a per-backend EWMA over real
    forwarded-predict outcomes and latency, refreshed between
    requests by a differential prober that POSTs a tiny canary
    predict (a recently-seen request body), and on each probe tick
    judges the EWMA: ``strikes`` CONSECUTIVE gray ticks (hysteresis —
    one slow answer cannot demote) start decaying the backend's
    effective routing weight by ``decay`` per tick; below
    ``eject_below`` the weight zeroes and the breaker is tripped
    (recovery rides the existing half-open path).  Healthy ticks
    reset the strikes and regrow the weight by ``recover``× per
    tick."""

    alpha: float = 0.3             # EWMA coefficient per observation
    min_observations: int = 3      # EWMA proves nothing before this
    ok_floor: float = 0.5          # ok-EWMA below this is gray
    latency_threshold_ms: float | None = None  # ms-EWMA above is gray
    strikes: int = 3               # consecutive gray ticks to demote
    decay: float = 0.5             # weight multiplier per gray tick
    eject_below: float = 0.05      # factor floor -> trip the breaker
    recover: float = 2.0           # factor regrowth per healthy tick
    canary_timeout_s: float = 5.0  # canary predict socket bound
    canary_max_bytes: int = 4096   # biggest body kept as template


class BackendDown(Exception):
    """Transport-level forward failure — the request never got an
    HTTP answer from this backend (vs. an HTTP error status, which is
    the backend's answer and passes through)."""


class Backend:
    """One serve process the router fronts.

    Holds the backend's base weight (live-adjustable — the rollout
    walk splits traffic by writing it), its circuit breaker (the
    ejection/re-admission state machine), a small keep-alive
    connection pool, and the most recent ``/healthz`` snapshot the
    background prober cached."""

    def __init__(self, url: str, *, name: str | None = None,
                 weight: float = 1.0,
                 breaker: CircuitBreaker | None = None,
                 timeout_s: float = 60.0, pool_size: int = 32):
        if not url.startswith(("http://", "https://")):
            raise ValueError(f"backend url must be http(s)://, "
                             f"got {url!r}")
        self.url = url if url.endswith("/") else url + "/"
        parts = urllib.parse.urlsplit(self.url)
        if parts.hostname is None or parts.port is None:
            raise ValueError(f"backend url needs an explicit "
                             f"host:port, got {url!r}")
        self.host = parts.hostname
        self.port = parts.port
        self.name = name or f"{self.host}:{self.port}"
        self.timeout_s = float(timeout_s)
        self.pool_size = int(pool_size)
        self.breaker = breaker if breaker is not None else \
            CircuitBreaker(failure_threshold=3, cooldown_s=2.0)
        self._lock = threading.Lock()
        self._weight = float(weight)
        self._pool: collections.deque = collections.deque()
        self._health: dict = {}
        self._health_at: float | None = None    # monotonic stamp
        #: device-time burn between the last two snapshots: an EWMA of
        #: Δ(Σ model device_ms)/Δwall in [0, ~1] per device — the
        #: engine_busy_ratio signal observed from the healthz rows the
        #: prober already fetches (placement's load input)
        self._busy = 0.0
        self._device_ms: float | None = None
        #: gray-failure detector state (router-driven: note_predict
        #: feeds the EWMAs from real forwards + canary probes,
        #: gray_step advances strikes/decay once per probe tick)
        self._p_ok = 1.0           # EWMA of predict success in [0, 1]
        self._p_ms = 0.0           # EWMA of predict latency, ms
        self._p_obs = 0            # observations folded so far
        self._gray_factor = 1.0    # effective-weight multiplier
        self._gray_strikes = 0     # consecutive gray probe ticks
        self._gray_episode = False  # demotion episode in progress
        #: smooth-WRR accumulator — owned (and locked) by the router's
        #: pick loop, not by this object
        self.wrr_current = 0.0

    # -- weight (live-adjustable: the rollout walk writes it) -------------
    @property
    def weight(self) -> float:
        with self._lock:
            return self._weight

    def set_weight(self, weight: float) -> None:
        if weight < 0:
            raise ValueError(f"weight must be >= 0, got {weight}")
        with self._lock:
            self._weight = float(weight)

    def effective_weight(self) -> float:
        """Base weight × the gray-failure factor: what the WRR pick
        actually spreads on.  The operator/rollout weight is never
        touched by demotion — recovery restores the split exactly."""
        with self._lock:
            return self._weight * self._gray_factor

    # -- gray-failure detector (the router's probe tick drives it) ---------
    def note_predict(self, ok: bool, ms: float,
                     alpha: float = 0.3) -> None:
        """Fold one real predict outcome (a forwarded request or a
        canary probe) into the EWMAs — timeouts and 5xx answers count
        as failures; 2xx–4xx are the backend answering."""
        with self._lock:
            self._p_ok = ((1.0 - alpha) * self._p_ok
                          + alpha * (1.0 if ok else 0.0))
            self._p_ms = (ms if self._p_obs == 0
                          else (1.0 - alpha) * self._p_ms + alpha * ms)
            self._p_obs += 1

    def predict_ewma(self) -> tuple[float, float, int]:
        """(ok EWMA, latency-ms EWMA, observations)."""
        with self._lock:
            return self._p_ok, self._p_ms, self._p_obs

    def gray_factor(self) -> float:
        with self._lock:
            return self._gray_factor

    def gray_step(self, gray: bool,
                  policy: "GrayPolicy") -> str | None:
        """Advance the hysteresis machine one probe tick.  Returns
        the transition this tick caused — ``"demoted"`` (strike
        threshold crossed, decay begins: count it), ``"ejected"``
        (factor fell through ``eject_below``: trip the breaker),
        ``"recovered"`` (factor regrew to 1.0) — or None."""
        with self._lock:
            if gray:
                self._gray_strikes += 1
                if self._gray_strikes < policy.strikes:
                    return None
                event = None
                if not self._gray_episode:
                    self._gray_episode = True
                    event = "demoted"
                if self._gray_factor > 0.0:
                    self._gray_factor *= policy.decay
                    if self._gray_factor < policy.eject_below:
                        self._gray_factor = 0.0
                        event = "ejected"
                return event
            self._gray_strikes = 0
            if self._gray_factor >= 1.0:
                return None
            self._gray_factor = min(
                1.0, max(self._gray_factor, policy.eject_below)
                * policy.recover)
            if self._gray_factor >= 1.0 and self._gray_episode:
                self._gray_episode = False
                return "recovered"
            return None

    # -- cached health snapshot (the prober writes it) ---------------------
    @staticmethod
    def _snapshot_device_ms(snapshot: dict) -> float | None:
        rows = snapshot.get("models")
        if not isinstance(rows, list):
            return None
        total, seen = 0.0, False
        for r in rows:
            if isinstance(r, dict) and r.get("device_ms") is not None:
                total += float(r["device_ms"])
                seen = True
        return total if seen else None

    def set_health(self, snapshot: dict) -> None:
        dev = self._snapshot_device_ms(snapshot)
        with self._lock:
            prev_dev, prev_at = self._device_ms, self._health_at
            self._health = dict(snapshot)
            self._health_at = time.monotonic()
            if dev is not None:
                if prev_dev is not None and prev_at is not None:
                    dt = self._health_at - prev_at
                    if dt > 0 and dev >= prev_dev:
                        ratio = (dev - prev_dev) / (dt * 1e3)
                        self._busy = 0.5 * self._busy + 0.5 * ratio
                self._device_ms = dev

    def busy_ratio(self) -> float:
        """Smoothed device-time burn fraction from the last probes
        (0.0 until two snapshots with device_ms rows landed)."""
        with self._lock:
            return self._busy

    def health(self) -> tuple[dict, float | None]:
        """(last /healthz snapshot, age in seconds) — ({}, None) until
        the first probe lands."""
        with self._lock:
            snap = dict(self._health)
            at = self._health_at
        age = None if at is None else time.monotonic() - at
        return snap, age

    def observe_generation(self, generation: int) -> None:
        """Fold a generation observed on a LIVE answer
        (``X-Model-Generation``) into the cached health snapshot — a
        backend that hot-swapped between probes breaks the router
        cache's consensus NOW instead of at the next probe tick."""
        with self._lock:
            if self._health.get("model_generation") != generation:
                self._health["model_generation"] = generation

    # -- the wire ----------------------------------------------------------
    def _acquire(self) -> tuple:
        """(connection, came_from_pool)."""
        with self._lock:
            if self._pool:
                return self._pool.pop(), True
        return self._new_conn(), False

    def _new_conn(self):
        return _http_client.HTTPConnection(self.host, self.port,
                                           timeout=self.timeout_s)

    def _release(self, conn, reusable: bool) -> None:
        if reusable:
            with self._lock:
                if len(self._pool) < self.pool_size:
                    self._pool.append(conn)
                    return
        conn.close()

    def _exchange(self, conn, method: str, path: str,
                  body: bytes | None,
                  headers: dict) -> tuple[int, bytes, dict]:
        conn.request(method, path, body, headers)
        resp = conn.getresponse()
        data = resp.read()
        self._release(conn, not resp.will_close)
        return resp.status, data, dict(resp.getheaders())

    def forward(self, method: str, path: str, body: bytes | None,
                headers: dict) -> tuple[int, bytes, dict]:
        """One HTTP exchange over a pooled keep-alive connection.
        Returns ``(status, body, response headers)``; raises
        :class:`BackendDown` on a transport-level failure (the
        connection is dropped, never returned to the pool).  A
        failure on a POOLED connection gets ONE fresh-connection
        retry first: an idle keep-alive socket the backend timed out
        is a staleness artifact of this pool, not evidence the
        backend is down — without the retry it would count toward
        ejecting a healthy backend."""
        conn, pooled = self._acquire()
        try:
            return self._exchange(conn, method, path, body, headers)
        except (OSError, _http_client.HTTPException) as e:
            conn.close()
            if not pooled:
                raise BackendDown(f"backend {self.name}: "
                                  f"{type(e).__name__}: {e}") from e
        conn = self._new_conn()
        try:
            return self._exchange(conn, method, path, body, headers)
        except (OSError, _http_client.HTTPException) as e:
            conn.close()
            raise BackendDown(f"backend {self.name}: "
                              f"{type(e).__name__}: {e}") from e

    def canary(self, method: str, path: str, body: bytes | None,
               headers: dict, *, timeout_s: float) -> int:
        """One probe exchange on a FRESH connection with its own
        (short) socket bound — never the pooled 60 s forward timeout,
        so a wedged backend costs the prober ``timeout_s``, not a
        probe-thread outage.  Returns the HTTP status; raises
        :class:`BackendDown` on transport failure or timeout."""
        conn = _http_client.HTTPConnection(self.host, self.port,
                                           timeout=float(timeout_s))
        try:
            conn.request(method, path, body, headers)
            resp = conn.getresponse()
            resp.read()
            return resp.status
        except (OSError, _http_client.HTTPException) as e:
            raise BackendDown(f"backend {self.name}: "
                              f"{type(e).__name__}: {e}") from e
        finally:
            conn.close()

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, collections.deque()
        for conn in pool:
            conn.close()

    def resident_models(self) -> list[str] | None:
        """Tenant names whose device weights the backend reported
        resident on its last probe (None = single-model backend, no
        zoo rows) — placement's affinity input."""
        snap, _age = self.health()
        rows = snap.get("models")
        if not isinstance(rows, list):
            return None
        return sorted(r["model"] for r in rows
                      if isinstance(r, dict) and r.get("model")
                      and r.get("resident"))

    def metrics(self) -> dict:
        snap, age = self.health()
        ok, ms, obs = self.predict_ewma()
        return {"name": self.name, "url": self.url,
                "weight": self.weight,
                "effective_weight": round(self.effective_weight(), 4),
                "gray": {"factor": round(self.gray_factor(), 4),
                         "ok_ewma": round(ok, 4),
                         "ewma_ms": round(ms, 2),
                         "observations": obs},
                "breaker": self.breaker.metrics(),
                "generation": snap.get("model_generation"),
                "backend_rev": snap.get("rev"),
                "backend_status": snap.get("status"),
                # the placement-relevant residency state, visible at
                # the router tier (scraped from backend healthz):
                # bytes on device + which tenants hold them
                "resident_bytes": snap.get("resident_bytes"),
                "resident_models": self.resident_models(),
                "busy_ratio": round(self.busy_ratio(), 4),
                "probe_age_s": (round(age, 1)
                                if age is not None else None)}


def _memo_key(generation: int, model: str | None, ctype: str,
              accept: str, body: bytes) -> bytes:
    """Router-tier cache key: the fleet generation, the routing model,
    BOTH wire formats (the request's Content-Type decides how the
    backend reads the body; the Accept decides what it answers), and
    the raw body bytes.  The router never parses payloads, so two
    JSON bodies that differ only in whitespace key separately — a
    cache miss, never a wrong answer."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((int(generation), model or "", ctype,
                   accept)).encode())
    h.update(body)
    return h.digest()


def _pack_response(ctype: str, body: bytes) -> np.ndarray:
    """(content-type, body) as one uint8 array — the ResponseCache
    stores arrays and accounts their nbytes, so the router's cached
    responses ride the same LRU/byte-budget machinery as serving's."""
    cb = ctype.encode("latin-1", "replace")
    head = len(cb).to_bytes(4, "little")
    return np.frombuffer(head + cb + body, np.uint8)


def _unpack_response(arr: np.ndarray) -> tuple[str, bytes]:
    blob = arr.tobytes()
    n = int.from_bytes(blob[:4], "little")
    return blob[4:4 + n].decode("latin-1"), blob[4 + n:]


def parse_backend_spec(spec: str) -> tuple[str, dict]:
    """``URL[,weight=W][,name=N]`` → (url, options) for the route CLI
    (same comma-option grammar as the serve CLI's --model specs)."""
    parts = spec.split(",")
    url = parts[0].strip()
    if not url:
        raise ValueError(f"empty backend url in spec {spec!r}")
    opts: dict = {}
    for part in parts[1:]:
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key = key.strip()
        if not sep or key not in ("weight", "name"):
            raise ValueError(
                f"bad backend option {part!r} in {spec!r} "
                f"(expected weight=W or name=N)")
        if key == "weight":
            try:
                opts["weight"] = float(value)
            except ValueError:
                raise ValueError(f"weight must be a number, "
                                 f"got {value!r}") from None
            if opts["weight"] < 0:
                raise ValueError(f"weight must be >= 0, got {value}")
        else:
            opts["name"] = value.strip()
    return url, opts


class FleetRouter:
    """The router process: N :class:`Backend` s behind one HTTP front
    (start()/stop()/url — the same lifecycle shape as
    :class:`~znicz_tpu.serving.server.ServingServer`)."""

    def __init__(self, backends, *, host: str = "127.0.0.1",
                 port: int = 0, default_deadline_ms: float | None = None,
                 probe_interval_s: float = 2.0,
                 admin_token: str | None = None,
                 max_body_mb: float = 64.0, max_hops: int = 2,
                 memo_entries: int = 0, memo_mb: float = 32.0,
                 placement: "placement_mod.PlacementEngine | None"
                 = None,
                 statestore:
                 "statestore_mod.StateStore | None" = None,
                 gray: GrayPolicy | None = None,
                 allow_empty: bool = False,
                 trace_sample: float = 1.0,
                 trace_head_rate: float = 0.05,
                 trace_tail_fraction: float = 0.05):
        if not backends and not allow_empty:
            raise ValueError("a router needs at least one backend")
        names = [b.name for b in backends]
        if len(set(names)) != len(names):
            raise ValueError(f"backend names must be unique, "
                             f"got {names}")
        self.backends: list[Backend] = list(backends)
        self.by_name = {b.name: b for b in self.backends}
        #: placement enforcement (docs/fleet.md): when an engine is
        #: attached, /predict routes a tenant only to its placed
        #: backends — failing over INSIDE the set first, then
        #: degrading to any healthy backend (never refusing because a
        #: set is empty).  None = the historical spread-over-everyone
        #: behavior, unchanged.
        self.placement = placement
        self._placement_lock = threading.Lock()
        #: (models, membership) key of the last computed plan — the
        #: prober recomputes when discovery changes it
        self._placement_key: tuple | None = None
        self._default_model: str | None = None
        self.default_deadline_ms = default_deadline_ms
        self.probe_interval_s = float(probe_interval_s)
        self.admin_token = admin_token
        self.max_body = int(max_body_mb * 1e6)
        #: transport-failure failover bound: how many DISTINCT
        #: backends one request may try (>= 1; the deadline can stop
        #: the loop earlier)
        self.max_hops = max(1, int(max_hops))
        #: router-tier response memoization (route --memoize; the
        #: PR 13 serving-tier pin lifted one tier): ONE cache for the
        #: whole fleet, reusing serving.memo.ResponseCache with the
        #: fleet_response_cache_* instruments.  Keyed on the fleet's
        #: single backend-reported generation — mixed generations
        #: (mid-roll) bypass it entirely; a store only lands when the
        #: answering backend's X-Model-Generation confirms the keyed
        #: generation, so a hot swap between health probes cannot
        #: poison the cache (the observed skew breaks consensus
        #: immediately via Backend.observe_generation).
        self.response_cache = (ResponseCache(
            max_entries=memo_entries, max_bytes=int(memo_mb * 1e6),
            instruments=(_fleet_cache_hits, _fleet_cache_misses,
                         _fleet_cache_bytes))
            if memo_entries > 0 else None)
        self.rev = buildinfo.cached_rev()
        #: control-plane durability (route --state-dir): every admin
        #: weight, pin, membership change and breaker ejection is
        #: journaled so a restarted router replays its decisions
        #: (docs/fleet.md "Control-plane durability")
        self.statestore = statestore
        #: leased high availability (fleet/ha.py): while ``_standby``
        #: is True this process does not hold the leadership lease —
        #: /predict and admin mutations answer 503 + Retry-After
        #: (with the primary's url as a hint) and only /healthz,
        #: /statusz, /metrics and /tracez serve.  Flag reads/writes
        #: are plain attribute ops (atomic under the GIL): the gate
        #: must never take a lock on the request path.
        self._standby = False
        self._ha = None
        #: gray-failure demotion policy (None = detector off: the
        #: EWMAs still fold, nothing decays)
        self.gray = gray
        self._reconcile_lock = threading.Lock()
        self._reconcile_until: float | None = None   # monotonic
        statestore_mod.set_reconcile_state(
            statestore_mod.RECONCILE_OFF if statestore is None
            else statestore_mod.RECONCILE_SETTLED)
        #: the differential prober's canary template: the most recent
        #: small request body a backend answered 200 — (ctype, accept,
        #: model, raw bytes)
        self._canary_lock = threading.Lock()
        self._canary_template: tuple | None = None
        #: breaker states at the last probe sweep, for journaling
        #: ejection transitions (audit records, not replayed state)
        self._breaker_seen: dict[str, str] = {}
        self._wrr_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._stopped = False
        self._requests = REGISTRY.counter(
            "requests_total",
            "HTTP requests answered, by route and status code")
        self._errors = REGISTRY.counter(
            "errors_total",
            "HTTP responses with status >= 400, by route and status "
            "code")
        #: optional status() of an in-process rollout driver
        #: (fleet.rollout.FleetTarget) — surfaced on /healthz, the
        #: same attach idiom as ServingServer.attach_promotion
        self.rollout_status = None
        #: optional status() of an in-process autoscaler loop
        #: (fleet.autoscaler.Autoscaler) — same attach idiom
        self.autoscale_status = None
        #: distributed tracing (ISSUE 18): the router is the fleet's
        #: root hop — it stamps a traceparent context on a
        #: deterministic ``trace_sample`` fraction of forwards (every
        #: request when a client already carries one), assembles the
        #: seven-stage trace from the backend's in-band span summary,
        #: and retains tail-first into this store (GET /tracez)
        self.trace_sample = min(1.0, max(0.0, float(trace_sample)))
        self.tracestore = tracestore.TraceStore(
            head_rate=trace_head_rate,
            tail_fraction=trace_tail_fraction)
        self._trace_counter = itertools.count(1)
        outer = self

        class Handler(FastHTTPHandler):

            def _route(self) -> str:
                path = self.path
                if path in _ROUTES:
                    return path
                path = path.split("?")[0].rstrip("/")
                return path if path in _ROUTES else "other"

            def _send(self, code: int, body: bytes, ctype: str,
                      headers: dict | None = None):
                self._status_code = code
                route = self._route()
                outer._requests.inc(route=route, code=str(code))
                if code >= 400:
                    outer._errors.inc(route=route, code=str(code))
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                rid = tracing.current_request_id()
                if rid is not None:
                    self.send_header("X-Request-Id", rid)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                if self.close_connection:
                    self.send_header("Connection", "close")
                if self.request_version != "HTTP/0.9":
                    self._headers_buffer.append(b"\r\n")
                    self._headers_buffer.append(body)
                    self.flush_headers()
                else:
                    self.wfile.write(body)

            def _reply(self, code: int, obj: dict,
                       headers: dict | None = None):
                self._send(code, json.dumps(obj, default=float).encode(),
                           "application/json", headers)

            def _read_body(self) -> bytes | None:
                """Content-Length-bounded body read — the same
                keep-alive framing pins as the serving front (501 on
                Transfer-Encoding, 400 on junk lengths, 413 over the
                bound; every early reply closes the connection so
                unread bytes can't desync the next request)."""
                if self.headers.get("Transfer-Encoding"):
                    self.close_connection = True
                    self._reply(501, {
                        "error": "Transfer-Encoding is not supported; "
                                 "send a Content-Length body"})
                    return None
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                except (TypeError, ValueError):
                    self.close_connection = True
                    self._reply(400, {"error": "bad request: junk "
                                               "Content-Length"})
                    return None
                if n < 0:
                    self.close_connection = True
                    self._reply(400, {"error": "bad request: negative "
                                               "Content-Length"})
                    return None
                if n > outer.max_body:
                    self.close_connection = True
                    self._reply(413, {
                        "error": f"body of {n} bytes exceeds the "
                                 f"{outer.max_body}-byte limit"})
                    return None
                return self.rfile.read(n) if n > 0 else b""

            def _admin_authorized(self) -> bool:
                if outer.admin_token is None:
                    return True
                import hmac
                supplied = self.headers.get("X-Admin-Token", "")
                return hmac.compare_digest(
                    supplied.encode("latin-1", "replace"),
                    outer.admin_token.encode("utf-8"))

            def do_GET(self):
                if self.headers.get("Content-Length") \
                        or self.headers.get("Transfer-Encoding"):
                    self.close_connection = True
                path = self.path.split("?")[0].rstrip("/")
                if path == "/healthz":
                    self._reply(200, outer.health())
                elif path == "/statusz":
                    self._send(200,
                               debugz.fleet_statusz_text(outer).encode(),
                               "text/plain; charset=utf-8")
                elif path == "/metrics":
                    query = (self.path.split("?", 1)[1]
                             if "?" in self.path else "")
                    accept = self.headers.get("Accept", "")
                    want_text = ("format=prometheus" in query
                                 or ("text/plain" in accept
                                     and "format=json" not in query))
                    if want_text:
                        self._send(200,
                                   REGISTRY.render_prometheus().encode(),
                                   PROMETHEUS_CONTENT_TYPE)
                    else:
                        self._reply(200, outer.metrics())
                elif path == "/tracez":
                    # the fleet-aggregated trace surface: assembled
                    # cross-hop traces, retention stats, exemplars
                    query = (self.path.split("?", 1)[1]
                             if "?" in self.path else "")
                    self._reply(200, outer.tracez(
                        **_tracez_filters(query)))
                else:
                    self._reply(404, {"error": f"no route {self.path!r}"})

            def do_POST(self):
                route = self.path.split("?")[0].rstrip("/")
                if route == "/admin/weight":
                    self._admin_weight()
                    return
                if route == "/admin/placement":
                    self._admin_placement()
                    return
                if route != "/predict":
                    self.close_connection = True   # body left unread
                    self._reply(404, {"error": f"no route {self.path!r}"})
                    return
                rid = tracing.accept_request_id(
                    self.headers.get("X-Request-Id"))
                # trace root (ISSUE 18): continue a client-supplied
                # context, else root a deterministic trace_sample
                # fraction of requests here (no RNG on this path)
                trace = tracing.parse_traceparent(
                    self.headers.get(tracestore.TRACE_HEADER))
                self._client_traced = trace is not None
                if trace is None and outer.trace_sample > 0.0:
                    stride = max(1, round(1.0 / outer.trace_sample))
                    if next(outer._trace_counter) % stride == 0:
                        trace = tracing.TraceContext(
                            tracing.new_trace_id(),
                            tracing.new_span_id())
                t0 = time.monotonic()
                started_at = time.time()
                self._status_code = None
                self._rec_error = None
                self._rec_backend = None
                self._trace_ctx = trace
                self._trace_pick_ms = 0.0
                self._trace_forward_ms = None
                self._trace_summary = None
                self._trace_model = None
                try:
                    with tracing.collect(rid) as collected:
                        with tracing.request(rid, trace=trace):
                            with tracing.span("router.predict"):
                                self._predict(t0)
                finally:
                    self._trace_ctx = None
                dt_ms = (time.monotonic() - t0) * 1e3
                # the router's own e2e latency signal (memo hits and
                # refusals included) — the autoscaler's burn input
                tracestore.observe_exemplar(_fleet_request_hist,
                                            dt_ms, trace)
                code = self._status_code or 500
                if trace is not None:
                    # assemble the hop-level trace — errors, sheds and
                    # refusals included: those are exactly the traces
                    # tail retention must never drop
                    tr = tracestore.assemble(
                        trace_id=trace.trace_id, request_id=rid,
                        model=self._trace_model or "default",
                        backend=self._rec_backend or "",
                        outcome=_outcome_of(code), total_ms=dt_ms,
                        pick_ms=self._trace_pick_ms,
                        forward_ms=self._trace_forward_ms,
                        summary=self._trace_summary,
                        started_at=started_at)
                    tracestore.observe_stages(tr)
                    outer.tracestore.record(tr)
                spans = [s.to_dict() for s in collected
                         if s._t0 >= t0]
                flightrecorder.RECORDER.record(
                    "request", duration_ms=dt_ms,
                    outcome="ok" if code < 400 else "error",
                    error=self._rec_error, request_id=rid, code=code,
                    backend=self._rec_backend,
                    stages=flightrecorder.stage_breakdown(spans),
                    spans=spans)

            def _admin_weight(self):
                """``POST /admin/weight`` — live traffic-split
                control: ``{"backend": name, "weight": W}``.  The
                rolling-promotion walk drives this to shift traffic
                toward/away from a canarying backend; token-gated
                exactly like the serving front's /admin/reload."""
                if not self._admin_authorized():
                    self.close_connection = True
                    self._reply(403, {
                        "error": "admin token required (supply "
                                 "X-Admin-Token)"})
                    return
                raw = self._read_body()
                if raw is None:
                    return
                refusal = outer.standby_refusal()
                if refusal is not None:
                    hdrs = {"Retry-After":
                            str(refusal["retry_after_s"])}
                    self._reply(503, refusal, hdrs)
                    return
                try:
                    payload = _json_object(raw)
                    name = payload["backend"]
                    weight = float(payload["weight"])
                except Exception as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                backend = outer.by_name.get(name)
                if backend is None:
                    self._reply(404, {
                        "error": f"no backend named {name!r} "
                                 f"(backends: "
                                 f"{sorted(outer.by_name)})"})
                    return
                if weight < 0:
                    self._reply(400, {"error": f"weight must be "
                                               f">= 0, got {weight}"})
                    return
                # journal FIRST: an un-journalable or fenced mutation
                # is refused before any in-memory state moves — a
                # weight that applied but didn't persist would
                # silently revert on the next failover
                refused = outer.journal_mutation(
                    "weight", backend=name, weight=weight)
                if refused is not None:
                    hdrs = {"Retry-After":
                            str(refused["retry_after_s"])}
                    self._reply(503, refused, hdrs)
                    return
                backend.set_weight(weight)
                self._reply(200, {"backend": name, "weight": weight})

            def _admin_placement(self):
                """``POST /admin/placement`` — live re-placement
                control, token-gated exactly like /admin/weight.
                Body is one of: ``{"action": "rebalance"}`` (recompute
                over the current membership + discovered tenants),
                ``{"model": m, "backends": [names]}`` (pin a tenant —
                beats scoring, survives recomputes), ``{"model": m,
                "backends": null}`` (clear the pin).  403 without the
                token, 400 on junk, 404 on an unknown backend name or
                on a router running without a placement engine."""
                if not self._admin_authorized():
                    self.close_connection = True
                    self._reply(403, {
                        "error": "admin token required (supply "
                                 "X-Admin-Token)"})
                    return
                raw = self._read_body()
                if raw is None:
                    return
                refusal = outer.standby_refusal()
                if refusal is not None:
                    hdrs = {"Retry-After":
                            str(refusal["retry_after_s"])}
                    self._reply(503, refusal, hdrs)
                    return
                if outer.placement is None:
                    self._reply(404, {
                        "error": "placement is not enabled on this "
                                 "router (route --placement N)"})
                    return
                try:
                    payload = _json_object(raw)
                    action = payload.get("action")
                    model = payload.get("model")
                    if action is None and model is None:
                        raise ValueError(
                            "expected {'action': 'rebalance'} or "
                            "{'model': ..., 'backends': [...]|null}")
                    if action is not None and action != "rebalance":
                        raise ValueError(
                            f"unknown action {action!r} (only "
                            f"'rebalance')")
                    if model is not None \
                            and not isinstance(model, str):
                        raise ValueError("'model' must be a name "
                                         "string")
                    pin = payload.get("backends")
                    if model is not None and pin is not None and (
                            not isinstance(pin, list)
                            or not pin
                            or not all(isinstance(n, str)
                                       for n in pin)):
                        raise ValueError(
                            "'backends' must be a non-empty list of "
                            "backend names, or null to clear the pin")
                except Exception as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                if model is not None and pin is not None:
                    unknown = [n for n in pin
                               if n not in outer.by_name]
                    if unknown:
                        self._reply(404, {
                            "error": f"no backend named "
                                     f"{unknown[0]!r} (backends: "
                                     f"{sorted(outer.by_name)})"})
                        return
                # journal FIRST (same discipline as /admin/weight)
                if model is not None:
                    refused = outer.journal_mutation(
                        "pin", model=model, backends=pin)
                else:
                    refused = outer.journal_mutation("rebalance")
                if refused is not None:
                    hdrs = {"Retry-After":
                            str(refused["retry_after_s"])}
                    self._reply(503, refused, hdrs)
                    return
                if model is not None:
                    outer.placement.pin(model, pin)
                    plan = outer.recompute_placement(cause="pin")
                else:
                    plan = outer.recompute_placement(cause="admin")
                self._reply(200, plan)

            def _predict(self, t0: float):
                raw = self._read_body()
                if raw is None:
                    return
                refusal = outer.standby_refusal()
                if refusal is not None:
                    # hot standby: honestly not serving.  Bounded
                    # 503 + Retry-After (one lease TTL) with the
                    # primary's url as the failover hint — never a
                    # silent forward from a replica that doesn't own
                    # the lease.
                    self._rec_error = "standby: not the primary"
                    hdrs = {"Retry-After":
                            str(refusal["retry_after_s"])}
                    self._reply(503, refusal, hdrs)
                    return
                ra = outer.reconcile_retry_after()
                if ra is not None:
                    # restart reconciliation in progress: the journal
                    # is replayed but children are not yet re-probed —
                    # routing now could land on a half-adopted
                    # backend.  Honest refusal, sized from the
                    # reconciliation deadline; never a hang, never a
                    # raw 500.
                    self._rec_error = "reconciling after restart"
                    self._reply(503, {
                        "error": "router restarting: control-plane "
                                 "reconciliation in progress",
                        "retry_after_s": ra},
                        {"Retry-After": str(ra)})
                    return
                try:
                    # the hop's header policy, re-pinned here: empty/
                    # whitespace values read as UNSET (a header-
                    # clearing proxy must not turn every request into
                    # a 400/404), junk values are the client's 400
                    model = self.headers.get("X-Model")
                    if model is not None:
                        model = model.strip() or None
                    crit = self.headers.get("X-Criticality")
                    if crit is not None:
                        crit = crit.strip().lower() or None
                        if crit is not None \
                                and crit not in overload.CRITICALITIES:
                            raise ValueError(
                                f"X-Criticality {crit!r}; expected "
                                f"one of {overload.CRITICALITIES}")
                    dl_raw = self.headers.get("X-Deadline-Ms")
                    if dl_raw is not None:
                        dl_raw = dl_raw.strip() or None
                    deadline_ms = (float(dl_raw) if dl_raw is not None
                                   else None)
                except Exception as e:
                    self._rec_error = f"bad request: {e}"
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                if deadline_ms is None:
                    deadline_ms = outer.default_deadline_ms
                deadline = overload.Deadline.from_ms(
                    deadline_ms, crit or "default")
                # router-tier memoization: a repeat body under the
                # fleet's ONE confirmed generation answers here with
                # no backend hop at all.  Mixed or unknown generations
                # (mid-roll, probes not landed) bypass — correctness
                # beats hit rate during a roll, the same stance as the
                # serving tier's replica-set pin.
                cache = outer.response_cache
                ckey = None
                memo_gen = None
                if cache is not None:
                    memo_gen = outer.memo_generation()
                    if memo_gen is not None:
                        ckey = _memo_key(
                            memo_gen, model,
                            self.headers.get("Content-Type")
                            or "application/json",
                            self.headers.get("Accept") or "", raw)
                        hit = cache.get(ckey)
                        if hit is not None:
                            ctype, body = _unpack_response(hit)
                            self._send(200, body, ctype,
                                       {"X-Fleet-Cache": "hit",
                                        "X-Model-Generation":
                                            str(memo_gen)})
                            return
                self._trace_model = model
                fwd = {"Content-Type":
                       (self.headers.get("Content-Type")
                        or "application/json"),
                       "X-Request-Id":
                       tracing.current_request_id() or ""}
                if self._trace_ctx is not None:
                    # stamp the hop context: same trace id, a fresh
                    # parent span id for THIS forward — the backend
                    # tags its span tree with it and returns its
                    # summary in-band for assembly
                    fwd[tracestore.TRACE_HEADER] = \
                        tracing.format_traceparent(tracing.TraceContext(
                            self._trace_ctx.trace_id,
                            tracing.new_span_id(),
                            self._trace_ctx.sampled))
                accept = self.headers.get("Accept")
                if accept:
                    fwd["Accept"] = accept
                if model is not None:
                    fwd["X-Model"] = model
                if crit is not None:
                    fwd["X-Criticality"] = crit
                tried: set = set()
                last_err: str | None = None
                pick_mode = "any"
                while len(tried) < outer.max_hops:
                    if deadline.at is not None \
                            and deadline.remaining_ms() <= 0.0:
                        # the budget died in (or before) the router —
                        # forwarding would burn a backend slot on an
                        # answer nobody is waiting for
                        overload.note_deadline("router")
                        self._rec_error = "deadline exceeded at router"
                        # Retry-After 0: the budget was the client's —
                        # an immediate retry with a fresh deadline is
                        # fine, the refusal just must not be header-
                        # silent (the 429/503/504 contract)
                        self._reply(504, {
                            "error": "deadline exceeded at the "
                                     "router hop"},
                            {"Retry-After": "0"})
                        return
                    t_p = time.monotonic()
                    backend, pick_mode = outer.pick_for(model,
                                                        exclude=tried)
                    # the router.pick_backend stage: accumulated over
                    # failover retries — re-picking IS pick cost
                    self._trace_pick_ms += \
                        (time.monotonic() - t_p) * 1e3
                    if backend is None:
                        break
                    if deadline.at is not None:
                        # re-issue the REMAINING budget to the
                        # backend: the hop's own latency (and any
                        # earlier failed hop) is already spent
                        fwd["X-Deadline-Ms"] = (
                            f"{max(0.0, deadline.remaining_ms()):.1f}")
                    t_f = time.monotonic()
                    try:
                        with tracing.span("router.forward",
                                          backend=backend.name):
                            status, data, rheaders = backend.forward(
                                "POST", "/predict", raw, fwd)
                    except BackendDown as e:
                        backend.breaker.record_failure()
                        backend.note_predict(
                            False, (time.monotonic() - t_f) * 1e3,
                            alpha=outer.gray_alpha())
                        _fleet_failovers.inc(backend=backend.name)
                        tried.add(backend.name)
                        last_err = str(e)
                        continue
                    dt = (time.monotonic() - t_f) * 1e3
                    _fleet_forward_hist.observe(dt,
                                                backend=backend.name)
                    # the wire trailer is consumed HERE regardless of
                    # this router's own sampling (a self-rooting
                    # backend may spill one): the client — and the
                    # memo cache below — must see the exact
                    # pre-trailer byte stream
                    data, trailer = wire_mod.split_trailer(data)
                    if self._trace_ctx is not None:
                        self._trace_forward_ms = dt
                        summary_raw = rheaders.get(
                            tracestore.SPANS_HEADER)
                        if trailer is not None:
                            summary_raw = trailer
                        self._trace_summary = \
                            tracestore.decode_summary(summary_raw)
                    # real-traffic half of the gray detector: 5xx
                    # answers and slow answers count against the
                    # backend's predict EWMA (a 4xx is the client's
                    # problem and the backend answering fine)
                    backend.note_predict(status < 500, dt,
                                         alpha=outer.gray_alpha())
                    backend.breaker.record_success()
                    _fleet_requests.inc(backend=backend.name,
                                        code=str(status))
                    self._rec_backend = backend.name
                    if status >= 500:
                        self._rec_error = (f"backend {backend.name} "
                                           f"answered {status}")
                    resp_gen = rheaders.get("X-Model-Generation")
                    if resp_gen is not None:
                        try:
                            resp_gen = int(resp_gen)
                        except ValueError:
                            resp_gen = None
                    if ckey is not None and status == 200 \
                            and resp_gen == memo_gen:
                        # store ONLY answers the backend stamped with
                        # the keyed generation: a swap between health
                        # probes must not file a new generation's
                        # bytes under the old key space
                        cache.put(ckey,
                                  _pack_response(
                                      rheaders.get("Content-Type",
                                                   "application/json"),
                                      data))
                    elif resp_gen is not None:
                        # observed skew: fold it into the cached
                        # health snapshot NOW — consensus breaks and
                        # the cache bypasses until probes re-converge
                        backend.observe_generation(resp_gen)
                    if outer.gray is not None and status == 200 \
                            and len(raw) \
                            <= outer.gray.canary_max_bytes:
                        # keep the freshest small 200-answered body as
                        # the differential prober's canary template —
                        # a probe that exercises the REAL predict
                        # path, not just /healthz
                        with outer._canary_lock:
                            outer._canary_template = (
                                fwd["Content-Type"], accept or "",
                                model, raw)
                    out = {"X-Fleet-Backend": backend.name}
                    if self._client_traced \
                            and self._trace_ctx is not None:
                        # the client carried its own traceparent:
                        # return the assembled stage split in-band so
                        # a tracing caller (bench --trace-breakdown)
                        # needs no second round-trip to /tracez
                        part = tracestore.assemble(
                            trace_id=self._trace_ctx.trace_id,
                            request_id=(tracing.current_request_id()
                                        or ""),
                            model=model or "default",
                            backend=backend.name,
                            outcome=_outcome_of(status),
                            total_ms=(time.monotonic() - t0) * 1e3,
                            pick_ms=self._trace_pick_ms,
                            forward_ms=self._trace_forward_ms,
                            summary=self._trace_summary,
                            started_at=time.time())
                        out[tracestore.SPANS_HEADER] = \
                            tracestore.encode_summary(
                                {"v": 1,
                                 "trace_id": part["trace_id"],
                                 "total_ms": part["total_ms"],
                                 "stages": part["stages"]}).decode()
                    if outer.placement is not None:
                        # placed = inside the tenant's set; degraded =
                        # the set could not take it and any-healthy
                        # answered; any = unplaced tenant
                        out["X-Fleet-Placement"] = pick_mode
                    ra = rheaders.get("Retry-After")
                    if ra is not None:
                        out["Retry-After"] = ra
                    self._send(status, data,
                               rheaders.get("Content-Type",
                                            "application/json"), out)
                    return
                # lost capacity: every candidate is ejected, cooling
                # down, or just failed under us — an honest refusal,
                # never a hang and never a raw 500
                ra = outer.retry_after()
                msg = ("no healthy backend available"
                       + (f" (last error: {last_err})" if last_err
                          else ""))
                self._rec_error = msg
                self._reply(503, {"error": msg, "retry_after_s": ra},
                            {"Retry-After": str(ra)})

        self.server = DeepBacklogHTTPServer((host, port), Handler)
        REGISTRY.register_collector(self._collect_fleet)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True,
                                        name="znicz-fleet-router")
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True,
                                        name="znicz-fleet-prober")

    # -- control-plane journal (route --state-dir) -------------------------
    def _journal(self, kind: str, **fields) -> None:
        """Durably record one control-plane mutation that ALREADY
        happened (membership, ejection audit).  Best-effort by
        design: a full disk must degrade durability, never take down
        the data plane.  A FENCED append additionally pokes the HA
        coordinator — a newer epoch owns the fleet and this process
        must demote (on the coordinator's thread, never this one)."""
        if self.statestore is None:
            return
        try:
            self.statestore.append(kind, **fields)
        except statestore_mod.FencedError as e:
            log.warning("control-plane journal append fenced "
                        "(%s): %s", kind, e)
            if self._ha is not None:
                self._ha.note_fenced()
        except OSError as e:
            log.warning("control-plane journal append failed "
                        "(%s: %s) — continuing without durability",
                        kind, e)

    def journal_mutation(self, kind: str, **fields) -> dict | None:
        """Journal-FIRST gate for admin mutations (weight, pin,
        rebalance): the record must be durable BEFORE the in-memory
        state changes.  Returns None when journaled (or no journal is
        attached — plain routers stay available), else a refusal body
        for an honest 503: an un-journalable mutation (ENOSPC — the
        ``statestore.append`` fault site) or a fenced one (a newer
        leadership epoch) is REFUSED, never half-applied, while reads
        and /predict keep serving."""
        if self.statestore is None:
            return None
        try:
            self.statestore.append(kind, **fields)
        except statestore_mod.FencedError as e:
            if self._ha is not None:
                self._ha.note_fenced()
            return {"error": f"mutation fenced: {e}",
                    "retry_after_s": self.retry_after()}
        except OSError as e:
            return {"error": f"control-plane journal unavailable "
                             f"({e}) — mutation refused, reads still "
                             f"serving",
                    "retry_after_s": self.retry_after()}
        return None

    # -- leased high availability (fleet/ha.py) ----------------------------
    def attach_ha(self, coordinator) -> None:
        """Surface an HA coordinator's role/epoch on ``/healthz`` /
        ``/statusz`` and let fenced journal appends trigger its
        demotion — the same attach idiom as :meth:`attach_rollout`."""
        self._ha = coordinator

    def set_standby(self, standby: bool) -> None:
        self._standby = bool(standby)

    def is_standby(self) -> bool:
        return self._standby

    def standby_refusal(self) -> dict | None:
        """The refusal body while this process is a hot standby
        (None when primary): 503-shaped, Retry-After sized to one
        lease TTL (by then either the primary answered or this
        standby owns the lease), with the primary's url as a
        failover hint for multi-url clients."""
        if not self._standby:
            return None
        ha = self._ha
        ra = (ha.retry_after_s() if ha is not None
              else self.retry_after())
        out = {"error": "standby router: this replica does not hold "
                        "the leadership lease",
               "retry_after_s": ra}
        primary = ha.primary_url() if ha is not None else None
        if primary:
            out["primary"] = primary
        return out

    def gray_alpha(self) -> float:
        return self.gray.alpha if self.gray is not None else 0.3

    # -- restart reconciliation (satellite: honest 503s meanwhile) ---------
    def begin_reconcile(self, deadline_s: float) -> None:
        """Enter the reconciliation window: until
        :meth:`end_reconcile` (or the deadline, whichever first),
        ``/predict`` answers 503 with Retry-After sized from the
        remaining deadline instead of routing at half-adopted
        backends."""
        with self._reconcile_lock:
            self._reconcile_until = time.monotonic() + float(deadline_s)
        statestore_mod.set_reconcile_state(
            statestore_mod.RECONCILE_RECONCILING)

    def end_reconcile(self) -> None:
        with self._reconcile_lock:
            self._reconcile_until = None
        statestore_mod.set_reconcile_state(
            statestore_mod.RECONCILE_SETTLED)

    def reconcile_retry_after(self) -> int | None:
        """Whole seconds of reconciliation left (ceil, >= 1) while
        the window is open; None once settled — including a blown
        deadline, where refusing forever would turn a slow reconcile
        into an outage."""
        with self._reconcile_lock:
            until = self._reconcile_until
        if until is None:
            return None
        left = until - time.monotonic()
        if left <= 0.0:
            return None
        return max(1, int(left) + (0 if left == int(left) else 1))

    # -- membership (live: the autoscaler adds/removes) --------------------
    def _backend_list(self) -> list[Backend]:
        with self._wrr_lock:
            return list(self.backends)

    def backend_count(self) -> int:
        with self._wrr_lock:
            return len(self.backends)

    def add_backend(self, backend: Backend) -> None:
        """Join one backend to the rotation (the autoscaler's
        scale-out path); placement re-runs on the new membership."""
        with self._wrr_lock:
            if backend.name in self.by_name:
                raise ValueError(f"backend name {backend.name!r} "
                                 f"already in rotation")
            self.backends.append(backend)
            self.by_name[backend.name] = backend
        self._journal("join", backend=backend.name, url=backend.url)
        self.recompute_placement(cause="join")

    def remove_backend(self, name: str) -> Backend:
        """Drop one backend from the rotation (scale-in: callers then
        drain the process); placement re-runs without it.  The last
        backend cannot leave — a router with nothing to route to
        answers nothing but 503s, which is an outage, not a scale-in."""
        with self._wrr_lock:
            if name not in self.by_name:
                raise KeyError(f"no backend named {name!r}")
            if len(self.backends) <= 1:
                raise ValueError("cannot remove the last backend")
            backend = self.by_name.pop(name)
            self.backends.remove(backend)
        self._journal("leave", backend=name)
        self.recompute_placement(cause="leave")
        return backend

    # -- routing ----------------------------------------------------------
    def pick(self, exclude=(), model: str | None = None
             ) -> Backend | None:
        """The next backend for one request (see :meth:`pick_for`)."""
        return self.pick_for(model, exclude)[0]

    def pick_for(self, model: str | None, exclude=()
                 ) -> tuple[Backend | None, str]:
        """(backend, mode) for one request.  With a placement engine
        attached and ``model`` placed, candidates are restricted to
        the placement set first (mode ``placed``); only when no
        placed backend can take the request does the pick degrade to
        the whole rotation (mode ``degraded`` — counted per model in
        ``placement_degraded_total``, never a refusal).  Unplaced
        tenants and placement-less routers route over everyone
        (mode ``any``)."""
        key = model
        if key is None:
            with self._placement_lock:
                key = self._default_model
        placed = (self.placement.placed(key)
                  if self.placement is not None else ())
        if placed:
            b = self._wrr_pick(exclude, restrict=set(placed))
            if b is not None:
                return b, "placed"
            placement_mod.note_degraded(key)
            b = self._wrr_pick(exclude)
            return b, "degraded"
        return self._wrr_pick(exclude), "any"

    def _wrr_pick(self, exclude=(), restrict=None) -> Backend | None:
        """Smooth weighted round-robin over the candidates whose
        breaker admits traffic (deterministic — no RNG on the request
        path).  ``exclude`` holds names this request already failed
        on; ``restrict`` (a name set) limits candidates to a
        placement set.  Consumes one breaker ``allow()`` per
        considered candidate; the chosen backend's outcome MUST be
        recorded (the forward loop does)."""
        with self._wrr_lock:
            # gray demotion multiplies into the spread here: base
            # weight × gray factor, so a predict-sick backend decays
            # out of rotation while its operator weight is preserved
            cands = [(b, b.effective_weight()) for b in self.backends
                     if b.name not in exclude
                     and (restrict is None or b.name in restrict)]
            weighted = [(b, w) for b, w in cands if w > 0]
            if not weighted:
                # every candidate is weighted out (a mid-walk dark
                # canary fleet-wide would be operator error; a fleet
                # gray-demoted to zero everywhere means nothing
                # better exists): fall back to equal weights rather
                # than refusing traffic
                weighted = [(b, 1.0) for b, _w in cands]
            total = sum(w for _b, w in weighted)
            for b, w in weighted:
                b.wrr_current += w
            ranked = sorted(weighted, key=lambda bw: -bw[0].wrr_current)
            if ranked:
                ranked[0][0].wrr_current -= total
        for b, _w in ranked:
            if b.breaker.allow():
                return b
        return None

    def memo_generation(self) -> int | None:
        """The fleet's single memoizable generation: every routable
        backend's last-reported ``model_generation`` must agree and be
        known — anything else (mid-roll skew, probes not landed, an
        ejected backend is ignored) returns None and the response
        cache bypasses.  Correctness beats hit rate during a roll."""
        gens: set = set()
        for b in self._backend_list():
            if b.breaker.state == "open":
                continue              # ejected: not serving traffic
            snap, _age = b.health()
            gens.add(snap.get("model_generation"))
        if len(gens) != 1:
            return None
        gen = gens.pop()
        return int(gen) if gen is not None else None

    def tracez(self, model: str | None = None,
               min_ms: float | None = None,
               outcome: str | None = None, n: int = 64) -> dict:
        """The fleet-aggregated trace surface behind ``GET /tracez``:
        assembled cross-hop traces (tail-first retention), store
        stats, and the exemplar trace ids currently pinned to the
        router's e2e latency buckets."""
        out = self.tracestore.snapshot(model=model, min_ms=min_ms,
                                       outcome=outcome, n=n)
        out["store"] = self.tracestore.stats()
        out["exemplars"] = {"fleet_request_latency_ms":
                            _fleet_request_hist.exemplars()}
        return out

    def retry_after(self) -> int:
        """Honest come-back time when no backend can take the
        request: the soonest any breaker could admit a probe,
        bounded [1, 30] seconds."""
        soonest = min((b.breaker.retry_after()
                       for b in self._backend_list()),
                      default=1.0)
        return max(1, min(30, int(soonest) + (0 if soonest ==
                                              int(soonest) else 1)))

    # -- placement ---------------------------------------------------------
    def _placement_inputs(self) -> tuple[list, list, str | None]:
        """(models, candidates, default model) from the cached probe
        snapshots — the scoring inputs of docs/fleet.md: per-tenant
        residency (model_resident lineage) and the backend's
        device-time burn (model_device_ms_total / engine_busy_ratio
        lineage), all read from the healthz rows the prober already
        fetches."""
        models: set = set()
        candidates = []
        default = None
        for b in self._backend_list():
            snap, _age = b.health()
            if b.breaker.state == "open":
                # ejected backends are not placement candidates: an
                # owner dying must move its tenants to live backends
                # on the next discovery sweep (the heal the chaos
                # placement drill pins), not leave them pointing at a
                # corpse.  Its discovered TENANTS still count — a
                # model only it held must stay in the map (degraded
                # routing answers it meanwhile)
                if isinstance(snap.get("models"), list):
                    for r in snap["models"]:
                        if isinstance(r, dict) and r.get("model"):
                            models.add(r["model"])
                continue
            rows = snap.get("models")
            resident: set = set()
            if isinstance(rows, list):
                for r in rows:
                    if isinstance(r, dict) and r.get("model"):
                        models.add(r["model"])
                        if r.get("resident"):
                            resident.add(r["model"])
            if default is None and snap.get("default_model"):
                default = snap["default_model"]
            candidates.append(placement_mod.PlacementCandidate(
                b.name, resident=resident, busy=b.busy_ratio()))
        return sorted(models), candidates, default

    def recompute_placement(self, cause: str = "manual") -> dict:
        """Re-run the placement plan over the current membership and
        discovered tenants, then push per-backend eviction hints down
        to each zoo (best-effort — a backend that misses a hint still
        converges through routing).  No-op without an engine."""
        if self.placement is None:
            return {}
        models, candidates, default = self._placement_inputs()
        plan = self.placement.plan(models, candidates, cause=cause)
        with self._placement_lock:
            self._default_model = default
            self._placement_key = (tuple(models),
                                   tuple(sorted(c.name
                                                for c in candidates)))
        self._push_placement_hints()
        return plan

    def _push_placement_hints(self) -> None:
        """Tell each backend's zoo which tenants it owns
        (``POST /admin/placement`` on the SERVE surface →
        ``ModelZoo.set_placement_hint``): non-placed device copies are
        released immediately and evict first under budget pressure —
        the fleet footprint bound is enforced at the source, not
        hoped for.  Best-effort per backend, bounded by the forward
        timeout."""
        if self.placement is None:
            return
        headers = {"Content-Type": "application/json"}
        if self.admin_token is not None:
            headers["X-Admin-Token"] = self.admin_token
        for b in self._backend_list():
            snap, _age = b.health()
            if not isinstance(snap.get("models"), list):
                continue               # single-model backend: no zoo
            body = json.dumps(
                {"models":
                 self.placement.backend_models(b.name)}).encode()
            try:
                b.forward("POST", "/admin/placement", body, headers)
            except BackendDown:
                pass                   # the prober will eject it

    def placement_status(self) -> dict | None:
        if self.placement is None:
            return None
        out = self.placement.status()
        with self._placement_lock:
            out["default_model"] = self._default_model
        return out

    # -- background prober -------------------------------------------------
    def _probe_loop(self) -> None:
        """Probe each backend's /healthz on a fixed cadence: keeps the
        aggregated /healthz fresh and gives an ejected backend a
        re-admission path even when no live request is willing to be
        its half-open probe."""
        while not self._stop_event.wait(self.probe_interval_s):
            for b in self._backend_list():
                if self._stop_event.is_set():
                    return
                self.probe_backend(b)
                self.canary_probe(b)
            self._gray_tick()
            self._note_ejections()
            self._maybe_recompute_placement()

    def _maybe_recompute_placement(self) -> None:
        """Discovery: recompute when the probe sweep changed the
        (tenants, membership) key — a new zoo entry appeared, a
        backend joined/left between sweeps.  Score drift alone never
        recomputes: cache affinity beats marginal balance."""
        if self.placement is None:
            return
        models, candidates, _default = self._placement_inputs()
        key = (tuple(models),
               tuple(sorted(c.name for c in candidates)))
        with self._placement_lock:
            stale = key != self._placement_key
        if stale and models:
            try:
                self.recompute_placement(cause="discovery")
            except Exception:
                pass                   # next sweep retries

    def probe_backend(self, backend: Backend) -> bool:
        """One /healthz probe, feeding the breaker (success closes a
        half-open circuit — re-admission; failure trips/keeps it
        open).  Respects the breaker's own probe pacing: an open
        circuit inside its cooldown is not hammered."""
        if not backend.breaker.allow():
            return False
        try:
            status, data, _h = backend.forward("GET", "/healthz", None,
                                               {})
            snap = json.loads(data)
            if status != 200 or not isinstance(snap, dict):
                raise BackendDown(f"healthz answered {status}")
        except (BackendDown, ValueError) as e:
            backend.breaker.record_failure()
            backend.set_health({"status": "unreachable",
                                "error": str(e)[:200]})
            return False
        backend.breaker.record_success()
        backend.set_health(snap)
        return True

    # -- gray-failure demotion (docs/fleet.md) ------------------------------
    def canary_probe(self, backend: Backend) -> bool | None:
        """The differential prober: POST a tiny canary predict (the
        most recent small 200-answered request body) at the backend —
        ``/healthz`` proves the process answers, the canary proves the
        PREDICT path does.  Feeds the same EWMA as real traffic; on a
        healthy backend fast canaries wash a one-off slow answer out
        of the EWMA before the hysteresis strikes out (one slow
        answer cannot demote).  None when the detector is off, no
        template was captured yet, or the breaker refuses the hop."""
        if self.gray is None:
            return None
        with self._canary_lock:
            tmpl = self._canary_template
        if tmpl is None or self.breaker_refuses(backend):
            return None
        ctype, accept, model, body = tmpl
        headers = {"Content-Type": ctype,
                   "X-Deadline-Ms":
                   f"{self.gray.canary_timeout_s * 1e3:.0f}"}
        if accept:
            headers["Accept"] = accept
        if model:
            headers["X-Model"] = model
        t_c = time.monotonic()
        try:
            status = backend.canary("POST", "/predict", body, headers,
                                    timeout_s=self.gray.canary_timeout_s)
            ok = status < 500
        except BackendDown:
            ok = False
        backend.note_predict(ok, (time.monotonic() - t_c) * 1e3,
                             alpha=self.gray.alpha)
        return ok

    @staticmethod
    def breaker_refuses(backend: Backend) -> bool:
        """True while the backend's circuit is open inside its
        cooldown — the canary must not burn the single half-open
        probe slot the healthz prober (or a live request) owns."""
        return backend.breaker.state == "open"

    def _gray_tick(self) -> None:
        """Judge each backend's predict EWMA once per probe sweep and
        advance its hysteresis machine: sustained gray decays the
        effective weight and ultimately trips the breaker; healthy
        ticks regrow it (recovery through the existing half-open
        path)."""
        if self.gray is None:
            return
        pol = self.gray
        for b in self._backend_list():
            ok, ms, obs = b.predict_ewma()
            if obs < pol.min_observations:
                continue
            gray = ok < pol.ok_floor or (
                pol.latency_threshold_ms is not None
                and ms > pol.latency_threshold_ms)
            event = b.gray_step(gray, pol)
            if event == "demoted":
                _gray_demotions.inc(backend=b.name)
                self._journal("ejection", backend=b.name,
                              source="gray", phase="demoted",
                              ok_ewma=round(ok, 4),
                              ewma_ms=round(ms, 2))
                log.warning("gray demotion: backend %s predict EWMA "
                            "ok=%.3f ms=%.1f — decaying effective "
                            "weight", b.name, ok, ms)
            elif event == "ejected":
                b.breaker.trip()
                self._journal("ejection", backend=b.name,
                              source="gray", phase="ejected")
                log.warning("gray ejection: backend %s effective "
                            "weight reached zero — breaker tripped",
                            b.name)
            elif event == "recovered":
                log.info("gray recovery: backend %s predict path "
                         "healthy again, full weight restored",
                         b.name)

    def _note_ejections(self) -> None:
        """Journal breaker ejection transitions observed since the
        last sweep (audit records — replay does not act on them)."""
        for b in self._backend_list():
            state = b.breaker.state
            if state == "open" \
                    and self._breaker_seen.get(b.name) != "open":
                self._journal("ejection", backend=b.name,
                              source="breaker")
            self._breaker_seen[b.name] = state

    # -- aggregated surfaces ----------------------------------------------
    def attach_rollout(self, status_fn) -> None:
        """Surface a rollout driver's ``status()`` on ``/healthz`` —
        the same idiom as ``ServingServer.attach_promotion``."""
        self.rollout_status = status_fn

    def attach_autoscaler(self, status_fn) -> None:
        """Surface an autoscaler loop's ``status()`` on ``/healthz``
        and ``/statusz`` — same idiom as :meth:`attach_rollout`."""
        self.autoscale_status = status_fn

    def backend_rows(self) -> list[dict]:
        return [b.metrics() for b in self._backend_list()]

    def health(self) -> dict:
        backends = self._backend_list()
        rows = [b.metrics() for b in backends]
        healthy = sum(1 for b in backends
                      if b.breaker.state != "open")
        status = ("ok" if healthy == len(backends)
                  else "degraded" if healthy else "unhealthy")
        out = {"status": status, "role": "router",
               "backends": rows,
               "healthy_backends": healthy,
               "backend_count": len(backends),
               "rev": self.rev,
               "uptime_s": round(debugz.process_uptime_s(), 1)}
        if self.statestore is not None:
            ra = self.reconcile_retry_after()
            out["reconcile"] = {
                "state": ("reconciling" if ra is not None
                          else "settled"),
                "journal": self.statestore.path}
            if ra is not None:
                out["reconcile"]["retry_after_s"] = ra
            if self.statestore.degraded:
                # honest degradation (ENOSPC): mutations refused,
                # reads still serving
                out["reconcile"]["degraded"] = True
        if self._ha is not None:
            # opt-in block, same rule as placement/autoscale: the
            # HA-less /healthz shape must not grow keys
            try:
                out["ha"] = self._ha.status()
            except Exception:
                out["ha"] = {"role": "unknown"}
        ps = self.placement_status()
        if ps is not None:
            # opt-in block, the zoo-surface rule: the placement-less
            # /healthz shape must not grow keys
            out["placement"] = ps
        a_s = self.autoscale_status
        if a_s is not None:
            try:
                out["autoscale"] = a_s()
            except Exception:
                out["autoscale"] = {"state": "unknown"}
        rs = self.rollout_status
        if rs is not None:
            try:
                out["rollout"] = rs()
            except Exception:
                out["rollout"] = {"state": "unknown"}
        if status != "ok":
            out["retry_after_s"] = self.retry_after()
        return out

    def metrics(self) -> dict:
        out = {"role": "router", "rev": self.rev,
               "backends": self.backend_rows(),
               "requests": {
                   "requests_total": int(self._requests.total()),
                   "errors_total": int(self._errors.total()),
                   "requests_by_route_code": self._requests.as_dict(),
                   "errors_by_route_code": self._errors.as_dict()},
               "fleet_requests_by_backend_code":
                   _fleet_requests.as_dict(),
               "failovers_by_backend": _fleet_failovers.as_dict()}
        if self.response_cache is not None:
            # opt-in block, same rule as the serving tier: the
            # pre-memo JSON surface must not grow keys
            out["response_cache"] = {
                **self.response_cache.metrics(),
                "generation": self.memo_generation()}
        return out

    def _collect_fleet(self):
        """Registry collector: the per-backend gauge families
        (healthy/weight/generation) plus the breaker-trip counter,
        sampled at scrape time — the ``fleet_*{backend=...}``
        inventory in docs/observability.md."""
        healthy, weights, gens, trips, ewmas = [], [], [], [], []
        for b in self._backend_list():
            labels = {"backend": b.name}
            healthy.append((labels,
                            0.0 if b.breaker.state == "open" else 1.0))
            weights.append((labels, float(b.weight)))
            snap, _age = b.health()
            gen = snap.get("model_generation")
            if gen is not None:
                gens.append((labels, float(gen)))
            trips.append((labels,
                          float(b.breaker.metrics().get("trips", 0))))
            _ok, ms, _obs = b.predict_ewma()
            ewmas.append((labels, float(ms)))
        fams = [
            ("gauge", "fleet_backend_healthy",
             "whether the router considers this backend routable "
             "(1) or ejected by its circuit breaker (0)", healthy),
            ("gauge", "fleet_backend_weight",
             "live routing weight per backend (the rolling-promotion "
             "walk shifts these to split traffic)", weights),
            ("counter", "fleet_backend_ejections_total",
             "circuit-breaker trips per backend at the router tier "
             "(closed/half_open -> open transitions)", trips),
            ("gauge", "backend_predict_ewma_ms",
             "EWMA of real forwarded-predict + canary-probe latency "
             "per backend, milliseconds — the gray-failure "
             "detector's latency signal (0 until the first "
             "observation)", ewmas)]
        if gens:
            fams.append((
                "gauge", "fleet_backend_generation",
                "serving generation per backend from its last "
                "/healthz probe — mixed values mid-roll are the "
                "generation skew the walk tolerates", gens))
        return fams

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FleetRouter":
        self._thread.start()
        self._prober.start()
        return self

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._stop_event.set()
        REGISTRY.unregister_collector(self._collect_fleet)
        self.server.shutdown()
        self.server.server_close()
        self._prober.join(5.0)
        for b in self._backend_list():
            b.close()

    @property
    def url(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}/"


def main(argv=None) -> int:
    """CLI entry for ``python -m znicz_tpu route``."""
    import argparse
    import os
    import signal

    p = argparse.ArgumentParser(
        prog="znicz_tpu route",
        description="fleet router: spread /predict over N serve "
                    "backends with weighted routing, per-backend "
                    "circuit breakers and failover (docs/fleet.md)")
    p.add_argument("--backend", action="append", metavar="SPEC",
                   default=[],
                   help="one serve backend: URL[,weight=W][,name=N] — "
                        "repeatable (e.g. "
                        "http://127.0.0.1:8101,weight=2,name=b0); "
                        "optional with --autoscale (the launcher "
                        "boots the floor)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--probe-interval-s", type=float, default=2.0,
                   help="background /healthz probe cadence per "
                        "backend (keeps the aggregated /healthz fresh "
                        "and re-admits recovered backends)")
    p.add_argument("--default-deadline-ms", type=float, default=None,
                   help="end-to-end deadline attached to requests "
                        "that send no X-Deadline-Ms (forwarded to the "
                        "backend as the remaining budget)")
    p.add_argument("--forward-timeout-s", type=float, default=60.0,
                   help="per-hop socket timeout for backend forwards")
    p.add_argument("--max-hops", type=int, default=2,
                   help="distinct backends one request may try when "
                        "transport-level forwards fail (failover "
                        "bound)")
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive transport failures before a "
                        "backend is ejected from rotation")
    p.add_argument("--breaker-cooldown-s", type=float, default=2.0,
                   help="seconds an ejected backend stays out before "
                        "a half-open probe may re-admit it")
    p.add_argument("--max-body-mb", type=float, default=64.0)
    p.add_argument("--memoize", type=int, default=0, metavar="N",
                   help="router-tier response memoization: keep up to "
                        "N recent (generation, body) -> response "
                        "entries and answer repeat requests with NO "
                        "backend hop (0 = off).  Keyed on the fleet's "
                        "single backend-reported generation "
                        "(X-Model-Generation); a mixed-generation "
                        "fleet — mid-roll — bypasses the cache "
                        "entirely (docs/fleet.md)")
    p.add_argument("--memoize-mb", type=float, default=32.0,
                   help="byte bound of the router response cache "
                        "(entries evict LRU-first under either "
                        "bound)")
    p.add_argument("--admin-token", default=None,
                   help="require this token (X-Admin-Token) on "
                        "POST /admin/weight and POST "
                        "/admin/placement; defaults to "
                        "$ZNICZ_ADMIN_TOKEN")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   metavar="RATE",
                   help="fraction of untraced requests the router "
                        "roots a distributed trace for "
                        "(deterministic stride, no RNG on the "
                        "request path; client-supplied traceparent "
                        "contexts are always honored; "
                        "docs/observability.md)")
    p.add_argument("--trace-head-rate", type=float, default=0.05,
                   metavar="RATE",
                   help="fraction of HEALTHY assembled traces the "
                        "store retains (every error/shed/deadline "
                        "trace and the slowest tail are always kept)")
    p.add_argument("--trace-tail-fraction", type=float, default=0.05,
                   metavar="FRAC",
                   help="slowest fraction of each model's recent "
                        "latency window that always wins retention "
                        "(the tail the p99 decomposition needs)")
    p.add_argument("--placement", type=int, default=0, metavar="R",
                   help="placement-aware routing: assign each zoo "
                        "tenant to R backends (weighted rendezvous, "
                        "residency-/load-scored) and route it only "
                        "there, degrading to any-healthy when the "
                        "set cannot answer (0 = off; docs/fleet.md)")
    d = p.add_argument_group(
        "control-plane durability (docs/fleet.md)")
    d.add_argument("--state-dir", default=None, metavar="DIR",
                   help="journal every control-plane mutation (admin "
                        "weights, pins, membership, autoscaler "
                        "boots/drains) to DIR/controlplane.jsonl and "
                        "replay it on restart: weights/pins are "
                        "restored and surviving autoscaler children "
                        "are re-adopted in place instead of "
                        "re-booted.  Changes the SIGTERM default to "
                        "journal-and-keep (see --teardown)")
    d.add_argument("--reconcile-deadline-s", type=float, default=30.0,
                   help="restart-reconciliation budget: until the "
                        "journaled children are re-probed (or this "
                        "deadline passes) /predict answers 503 with "
                        "Retry-After sized from the remainder")
    d.add_argument("--teardown", action="store_true",
                   help="drain every managed backend on shutdown "
                        "even with --state-dir (the pre-state-dir "
                        "behavior; without --state-dir teardown is "
                        "always on — there is no journal to re-adopt "
                        "from)")
    ha_g = p.add_argument_group(
        "high availability (docs/fleet.md 'Router high "
        "availability') — leased leadership over --state-dir: the "
        "primary renews DIR/lease.json on a tick; a standby tails "
        "the journal, probes the primary, and takes over (bumping "
        "the fencing epoch) when the lease expires")
    ha_g.add_argument("--standby-of", default=None, metavar="URL",
                      help="start as a hot standby of the primary at "
                           "URL: refuse /predict and admin mutations "
                           "with 503 + Retry-After, tail the journal "
                           "to keep weights/pins/children warm, and "
                           "take over on lease expiry (requires "
                           "--state-dir on the SAME directory)")
    ha_g.add_argument("--peer", default=None, metavar="URL",
                      help="symmetric HA: race for the lease at boot "
                           "— winner serves, loser runs as a hot "
                           "standby of URL (requires --state-dir; "
                           "mutually exclusive with --standby-of)")
    ha_g.add_argument("--lease-ttl-s", type=float, default=3.0,
                      help="leadership lease TTL: a standby may take "
                           "over this long after the primary's last "
                           "renewal (failover completes within ~2x "
                           "this; standby 503s advertise it as "
                           "Retry-After)")
    ha_g.add_argument("--lease-renew-s", type=float, default=None,
                      help="primary renew tick (default: ttl/3)")
    d.add_argument("--no-gray-demotion", dest="gray",
                   action="store_false", default=True,
                   help="disable gray-failure demotion (on by "
                        "default: a probe-green backend whose real "
                        "predicts fail or stall has its effective "
                        "weight decayed toward zero and is "
                        "ultimately ejected)")
    d.add_argument("--gray-threshold-ms", type=float, default=None,
                   help="predict-latency EWMA above which a backend "
                        "counts as gray (default: error ratio only)")
    d.add_argument("--gray-strikes", type=int, default=3,
                   help="consecutive gray probe ticks before the "
                        "weight decay starts (the hysteresis: one "
                        "slow answer never demotes)")
    d.add_argument("--gray-decay", type=float, default=0.5,
                   help="effective-weight multiplier applied per "
                        "gray probe tick past the strike threshold")
    g = p.add_argument_group(
        "autoscaling (route --autoscale / python -m znicz_tpu "
        "autoscale)")
    g.add_argument("--autoscale", action="store_true",
                   help="run the elastic autoscaler loop: boot serve "
                        "processes on sustained burn, drain them "
                        "gracefully on sustained idle (docs/fleet.md)")
    g.add_argument("--serve-arg", action="append", default=[],
                   metavar="ARG",
                   help="one argument appended to every booted "
                        "'serve' process (repeatable; e.g. "
                        "--serve-arg=--zoo --serve-arg=zoo_dir)")
    g.add_argument("--min-backends", type=int, default=1,
                   help="membership floor: never drain below this "
                        "(static --backend entries count toward it "
                        "and are never drained themselves)")
    g.add_argument("--max-backends", type=int, default=4,
                   help="membership ceiling: never boot above this")
    g.add_argument("--autoscale-interval-s", type=float, default=5.0,
                   help="sampling-window length of the scale loop")
    g.add_argument("--autoscale-objective", default="availability",
                   help="burn objective judged per window "
                        "(availability | latency)")
    g.add_argument("--autoscale-target", type=float, default=0.999,
                   help="SLO target the burn budget derives from")
    g.add_argument("--autoscale-threshold-ms", type=float,
                   default=None,
                   help="latency-objective threshold (required when "
                        "the objective is latency)")
    g.add_argument("--autoscale-max-burn", type=float, default=2.0,
                   help="burn rate a window must reach to count as "
                        "hot")
    g.add_argument("--autoscale-min-events", type=int, default=5,
                   help="fewer events than this in a window proves "
                        "nothing (burns 0, same stance as the SLO "
                        "engine)")
    g.add_argument("--breach-windows", type=int, default=2,
                   help="CONSECUTIVE hot windows before a scale-out "
                        "(the hysteresis: one blip never boots)")
    g.add_argument("--idle-windows", type=int, default=6,
                   help="consecutive quiet windows before a "
                        "scale-in")
    g.add_argument("--idle-rps", type=float, default=0.5,
                   help="request rate under which a no-burn window "
                        "counts as quiet")
    g.add_argument("--autoscale-cooldown-s", type=float, default=30.0,
                   help="hold-down after any membership action")
    g.add_argument("--drain-timeout-s", type=float, default=20.0,
                   help="graceful-drain window granted to a retiring "
                        "backend before SIGKILL")
    g.add_argument("--boot-timeout-s", type=float, default=60.0,
                   help="how long a booting backend may take to "
                        "answer /healthz before the boot fails")
    g.add_argument("--autoscale-log-dir", default=None,
                   help="directory for booted backends' logs "
                        "(default: discard)")
    g.add_argument("--crash-loop-threshold", type=int, default=3,
                   help="boot failures inside --crash-loop-window-s "
                        "that stop the boot loop for good (sticky, "
                        "with the failing child's log tail printed) "
                        "— a child that dies instantly on every boot "
                        "means the serve command is broken")
    g.add_argument("--crash-loop-window-s", type=float, default=60.0,
                   help="sliding window the crash-loop threshold "
                        "counts boot failures over")
    args = p.parse_args(argv)
    if not args.backend and not args.autoscale:
        p.error("at least one --backend is required (or --autoscale, "
                "which boots its own)")
    if args.autoscale and not args.serve_arg and \
            len(args.backend) < max(1, args.min_backends):
        p.error("--autoscale needs --serve-arg ... to know how to "
                "boot backends (e.g. --serve-arg=--zoo "
                "--serve-arg=DIR), or enough static --backend "
                "entries to cover --min-backends")
    if args.placement < 0:
        p.error("--placement must be >= 0")
    if args.standby_of and args.peer:
        p.error("--standby-of and --peer are mutually exclusive "
                "(--standby-of starts as standby; --peer races for "
                "the lease)")
    if (args.standby_of or args.peer) and not args.state_dir:
        p.error("--standby-of/--peer need --state-dir: the lease and "
                "the journal live there, shared by both replicas")
    if args.lease_ttl_s <= 0:
        p.error("--lease-ttl-s must be > 0")
    if args.gray_strikes < 1:
        p.error("--gray-strikes must be >= 1")
    if not 0.0 < args.gray_decay < 1.0:
        p.error("--gray-decay must be in (0, 1)")
    token = args.admin_token if args.admin_token is not None \
        else os.environ.get("ZNICZ_ADMIN_TOKEN") or None
    gray_policy = (GrayPolicy(
        latency_threshold_ms=args.gray_threshold_ms,
        strikes=args.gray_strikes, decay=args.gray_decay)
        if args.gray else None)
    store = (statestore_mod.StateStore(args.state_dir)
             if args.state_dir else None)
    replayed = store.replay() if store is not None else None
    backends = []
    for i, spec in enumerate(args.backend):
        try:
            url, opts = parse_backend_spec(spec)
            backends.append(Backend(
                url, name=opts.get("name", f"b{i}"),
                weight=opts.get("weight", 1.0),
                timeout_s=args.forward_timeout_s,
                breaker=CircuitBreaker(
                    failure_threshold=args.breaker_threshold,
                    cooldown_s=args.breaker_cooldown_s)))
        except ValueError as e:
            p.error(str(e))
    engine = (placement_mod.PlacementEngine(args.placement)
              if args.placement > 0 else None)
    launcher = None
    scaler = None
    booted = []
    router = None
    coordinator = None
    try:
        if args.autoscale:
            from .autoscaler import Autoscaler, ServeLauncher
            launcher = ServeLauncher(
                args.serve_arg, host=args.host,
                log_dir=args.autoscale_log_dir,
                boot_timeout_s=args.boot_timeout_s,
                forward_timeout_s=args.forward_timeout_s,
                breaker_threshold=args.breaker_threshold,
                breaker_cooldown_s=args.breaker_cooldown_s)
            # Without a journal the floor boots BEFORE the router (it
            # needs >= 1 backend).  With one, journaled children are
            # reconciled AFTER the router is up — it answers honest
            # 503s meanwhile — so nothing is double-booted: the floor
            # only covers what reconciliation could not re-adopt.
            if store is None:
                while len(backends) + len(booted) \
                        < max(1, args.min_backends):
                    b, proc = launcher.spawn(len(booted))
                    booted.append((b, proc))
                    print(f"autoscale: booted floor backend {b.name} "
                          f"at {b.url}", flush=True)
        router = FleetRouter(
            backends + [b for b, _p in booted],
            host=args.host, port=args.port,
            default_deadline_ms=args.default_deadline_ms,
            probe_interval_s=args.probe_interval_s,
            admin_token=token, max_body_mb=args.max_body_mb,
            max_hops=args.max_hops, memo_entries=args.memoize,
            memo_mb=args.memoize_mb, placement=engine,
            statestore=store, gray=gray_policy,
            trace_sample=args.trace_sample,
            trace_head_rate=args.trace_head_rate,
            trace_tail_fraction=args.trace_tail_fraction,
            allow_empty=store is not None and args.autoscale)
        primary = True
        if store is not None:
            # HA is always on with a state dir: a solo router simply
            # holds an uncontested lease (epoch 1).  --standby-of
            # starts watching; --peer (or a plain second route over a
            # LIVE lease) races and the loser auto-demotes — a
            # resurrected old primary rejoins as a fenced standby.
            from . import ha as ha_mod
            coordinator = ha_mod.HACoordinator(
                store, url=router.url,
                peer_url=args.standby_of or args.peer,
                ttl_s=args.lease_ttl_s,
                renew_interval_s=args.lease_renew_s)
            primary = (False if args.standby_of
                       else coordinator.try_acquire())
            if primary:
                router.begin_reconcile(args.reconcile_deadline_s)
            else:
                router.set_standby(True)
        router.start()
        if args.autoscale:
            scaler = Autoscaler(
                router, launcher=launcher,
                min_backends=max(1, args.min_backends),
                max_backends=args.max_backends,
                interval_s=args.autoscale_interval_s,
                objective=args.autoscale_objective,
                target=args.autoscale_target,
                threshold_ms=args.autoscale_threshold_ms,
                max_burn_rate=args.autoscale_max_burn,
                min_events=args.autoscale_min_events,
                breach_windows=args.breach_windows,
                idle_windows=args.idle_windows,
                idle_rps=args.idle_rps,
                cooldown_s=args.autoscale_cooldown_s,
                drain_timeout_s=args.drain_timeout_s,
                crash_loop_threshold=args.crash_loop_threshold,
                crash_loop_window_s=args.crash_loop_window_s,
                statestore=store)
            for b, proc in booted:
                scaler.adopt(b, proc)
        if store is not None:
            def _on_promote(state):
                # takeover: close the gate last — reconcile first so
                # the first served request lands on adopted, probed
                # backends, not half-warm ones
                router.begin_reconcile(args.reconcile_deadline_s)
                router.set_standby(False)
                ha_mod.settle_control_plane(
                    router, scaler, launcher, store, state,
                    reconcile_deadline_s=args.reconcile_deadline_s,
                    min_backends=max(1, args.min_backends))
                if scaler is not None:
                    scaler.start()

            def _on_demote():
                # children are NOT drained: the new primary owns them
                router.set_standby(True)
                if scaler is not None:
                    scaler.stop()

            coordinator.attach(router=router, promote=_on_promote,
                               demote=_on_demote)
            if scaler is not None:
                scaler.on_fenced = coordinator.note_fenced
            if primary:
                ha_mod.settle_control_plane(
                    router, scaler, launcher, store, replayed,
                    reconcile_deadline_s=args.reconcile_deadline_s,
                    min_backends=max(1, args.min_backends))
            else:
                print(f"ha: standby (epoch "
                      f"{coordinator.lease.observed_epoch()} held "
                      f"elsewhere) — tailing the journal, refusing "
                      f"traffic with 503 + Retry-After until the "
                      f"lease is ours", flush=True)
            coordinator.start()
        if scaler is not None and primary:
            scaler.start()
        names = [b.name for b in router._backend_list()]
        print(f"routing {len(names)} backend(s) {names} at "
              f"{router.url} (POST /predict, GET /healthz, "
              f"GET /metrics, GET /statusz, POST /admin/weight, "
              f"POST /admin/placement"
              + (f"; placement replication={args.placement}"
                 if engine is not None else "")
              + ("; autoscale on" if scaler is not None else "")
              + (f"; ha {'primary' if primary else 'standby'} "
                 f"epoch {coordinator.epoch}"
                 if coordinator is not None else "")
              + ")", flush=True)
        stop = threading.Event()

        def _arm():
            signal.signal(signal.SIGINT, lambda *_: stop.set())
            signal.signal(signal.SIGTERM, lambda *_: stop.set())
        _arm()
        while not stop.is_set():
            # short ticks so signal handlers run promptly even if a
            # native lib clobbers the process sigaction — the same
            # idiom (and reason) as the serve CLI's loop
            stop.wait(0.5)
            _arm()
    except KeyboardInterrupt:
        pass
    finally:
        if coordinator is not None:
            # step down FIRST: back-dating the lease lets the peer
            # take over immediately instead of waiting out the TTL
            coordinator.stop()
        if scaler is not None:
            # without a journal: drain every managed backend
            # gracefully (SIGTERM → the serve drain path → exit 0),
            # THEN stop routing.  With --state-dir the default flips
            # to journal-and-keep — children survive for re-adoption
            # — unless --teardown restores the drain-everything path.
            scaler.shutdown(teardown=args.teardown or store is None)
        elif booted:
            for b, proc in booted:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except Exception:
                    proc.kill()
        if router is not None:
            router.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
