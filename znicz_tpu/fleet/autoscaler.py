"""Elastic fleet autoscaling on the burn-rate signal.

The placement engine (:mod:`znicz_tpu.fleet.placement`) decides where
tenants live on a FIXED membership; this module makes the membership
itself elastic.  ``python -m znicz_tpu route --autoscale`` (alias:
``python -m znicz_tpu autoscale``) runs an :class:`Autoscaler` loop
inside the router process that boots and drains REAL ``serve``
processes:

* **Scale-out on sustained burn** — each tick samples the router's
  own request-path signals (``requests_total`` / ``errors_total`` on
  the ``/predict`` route plus the ``fleet_request_latency_ms``
  histogram) and computes the window's error-budget burn with the
  PR 12 arithmetic (:func:`znicz_tpu.telemetry.sloengine.burn_between`
  — the same code the pager and the canary judge run).  Only
  ``breach_windows`` CONSECUTIVE burning windows trigger a boot: a
  one-window blip is hysteresis-filtered, exactly like the burn-rate
  canary's fast+slow gate.
* **Scale-in through graceful drain** — ``idle_windows`` consecutive
  quiet windows (no burn, request rate under ``idle_rps``) retire the
  most recently booted managed backend: it leaves the router's
  rotation first, then receives SIGTERM and drains via the PR 10
  graceful path (503 + Retry-After, bounded batcher drain, exit 0).
  Only backends the autoscaler itself booted are ever retired — the
  operator's static ``--backend`` floor is never drained.
* **Placement follows membership** — ``FleetRouter.add_backend`` /
  ``remove_backend`` re-run placement on every membership change, so
  tenants re-shard onto the new capacity (and off the draining one)
  automatically.
* **Cooldown** — after any action the loop holds ``cooldown_s``
  before acting again: a boot takes seconds to absorb load, and
  judging its effect mid-boot would flap.
* **Crash-loop fail-fast** — ``crash_loop_threshold`` boot failures
  inside ``crash_loop_window_s`` stop the boot loop for good (sticky
  until an operator restarts), printing the failing child's log tail
  — the ElasticRunner discipline (parallel/elastic.py): a child that
  dies instantly on every boot means the *command* is broken, and
  hot-looping boots just burns pids and disk while hiding the real
  error.  ``autoscaler_crash_loops_total`` counts the trips.
* **Epoch fencing** (fleet/ha.py) — when leased HA is active, every
  boot and drain re-checks :meth:`StateStore.fenced` first: a
  deposed primary (newer epoch in the lease) never double-boots or
  double-drains a backend the new primary now owns, and ``shutdown``
  flips to journal-and-keep for the same reason.

Families: ``autoscale_backends``, ``autoscale_events_total
{direction}``, ``autoscale_burn_rate``,
``autoscaler_crash_loops_total`` (docs/observability.md).  The
loop's state is surfaced on the router's ``/healthz``/``/statusz``
via ``router.attach_autoscaler`` — the same attach idiom as the
rollout driver.

Testability: the sampling, spawning and retiring are all injectable
(``sample_fn`` / ``spawn`` / ``retire``), and :meth:`Autoscaler.tick`
is a plain method — tier-1 tests drive the hysteresis state machine
with fake samples and no processes (tests/test_placement.py).
"""

from __future__ import annotations

import collections
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
from urllib.parse import urlsplit

from ..promotion.slo import SLOSample, _route_code_sum
from ..resilience.breaker import CircuitBreaker
from ..telemetry import sloengine
from ..telemetry.registry import (DEFAULT_LATENCY_BUCKETS_MS, REGISTRY)
from .router import Backend, BackendDown
from .statestore import (FencedError, OrphanProcess, _backend_adopted,
                         _fenced_mutations, pid_alive,
                         process_identity)

_backends_g = REGISTRY.gauge(
    "autoscale_backends",
    "backends currently in the router's rotation while the "
    "autoscaler loop runs (static --backend floor plus booted "
    "managed ones)")
_events = REGISTRY.counter(
    "autoscale_events_total",
    "autoscaler membership actions, by direction (out = booted a "
    "serve process on sustained burn | in = drained one on sustained "
    "idle)")
_burn_g = REGISTRY.gauge(
    "autoscale_burn_rate",
    "error-budget burn rate of the autoscaler's last sampling window "
    "over the router's own request-path signals (the scale-out "
    "trigger, sloengine.burn_between arithmetic)")
_crash_loops = REGISTRY.counter(
    "autoscaler_crash_loops_total",
    "boot loops stopped by the crash-loop fail-fast: "
    "crash_loop_threshold immediate boot failures inside "
    "crash_loop_window_s — the serve command itself is broken; the "
    "loop stays stopped (with the failing child's log tail printed) "
    "until an operator intervenes")


def router_sample() -> SLOSample:
    """Snapshot the ROUTER-tier SLO signals from the process-wide
    registry: the router's ``/predict`` request/5xx counters and its
    end-to-end request latency histogram.  Same normalized shape the
    promotion watch speaks, so :func:`sloengine.burn_between` applies
    unchanged.  Instrument lookups are get-or-create — sampled before
    the first request it reads zeros."""
    hist = REGISTRY.histogram("fleet_request_latency_ms",
                              buckets=DEFAULT_LATENCY_BUCKETS_MS)
    h = hist.as_dict()
    if "buckets" not in h:
        h = {"buckets": {}, "count": 0.0}
    latency_cum = {sloengine._edge_of(k): float(v)
                   for k, v in h["buckets"].items()}
    requests = _route_code_sum(
        REGISTRY.counter("requests_total").as_dict(), "/predict")
    errors = _route_code_sum(
        REGISTRY.counter("errors_total").as_dict(), "/predict",
        min_code=500)
    return SLOSample(at=time.time(), latency_cum=latency_cum,
                     latency_count=float(h["count"]),
                     requests=requests, errors_5xx=errors)


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class ServeLauncher:
    """Boots/drains real ``python -m znicz_tpu serve`` subprocesses.

    ``serve_args`` is the argument tail every booted backend gets
    (``--zoo DIR --memory-budget-mb 64`` …); the launcher owns the
    port, the log file, and the bounded healthz boot wait."""

    def __init__(self, serve_args, *, host: str = "127.0.0.1",
                 log_dir: str | None = None,
                 boot_timeout_s: float = 60.0,
                 forward_timeout_s: float = 60.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 2.0):
        self.serve_args = list(serve_args)
        self.host = host
        self.log_dir = log_dir
        self.boot_timeout_s = float(boot_timeout_s)
        self.forward_timeout_s = float(forward_timeout_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)

    def _log_file(self, name: str):
        if self.log_dir is None:
            return subprocess.DEVNULL
        os.makedirs(self.log_dir, exist_ok=True)
        return open(os.path.join(self.log_dir, f"{name}.log"), "ab")

    def log_tail(self, name: str, lines: int = 20) -> str | None:
        """The last ``lines`` lines of one child's log (None without
        a log dir or file) — what the crash-loop fail-fast prints so
        the operator sees WHY the boots die instead of a bare
        counter."""
        if self.log_dir is None:
            return None
        path = os.path.join(self.log_dir, f"{name}.log")
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            return None
        tail = data.decode("utf-8", "replace").splitlines()[-lines:]
        return "\n".join(tail) if tail else None

    def spawn(self, index: int) -> tuple[Backend, subprocess.Popen]:
        """Boot one serve process and wait (bounded) for its /healthz;
        returns a routable :class:`Backend` + the process handle.  A
        boot that never answers is killed and raised — a half-up
        backend must not enter rotation."""
        port = _free_port(self.host)
        name = f"as{index}"
        cmd = [sys.executable, "-m", "znicz_tpu", "serve",
               "--host", self.host, "--port", str(port)] \
            + self.serve_args
        log = self._log_file(name)
        proc = subprocess.Popen(cmd, stdout=log, stderr=log)
        if log is not subprocess.DEVNULL:
            log.close()                    # the child holds its own fd
        backend = Backend(
            f"http://{self.host}:{port}/", name=name,
            timeout_s=self.forward_timeout_s,
            breaker=CircuitBreaker(
                failure_threshold=self.breaker_threshold,
                cooldown_s=self.breaker_cooldown_s))
        deadline = time.monotonic() + self.boot_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"serve backend {name} exited rc={proc.returncode} "
                    f"before answering /healthz (cmd: {' '.join(cmd)})")
            try:
                status, data, _h = backend.forward("GET", "/healthz",
                                                   None, {})
                if status == 200:
                    snap = json.loads(data)
                    if isinstance(snap, dict):
                        backend.set_health(snap)
                    return backend, proc
            except (BackendDown, ValueError):
                pass
            time.sleep(0.2)
        proc.kill()
        proc.wait(timeout=10)
        raise RuntimeError(f"serve backend {name} did not answer "
                           f"/healthz within {self.boot_timeout_s}s")

    def retire(self, backend: Backend, proc: subprocess.Popen, *,
               drain_timeout_s: float = 20.0) -> int | None:
        """SIGTERM → the serve process's graceful drain (PR 10: 503 +
        Retry-After, bounded batcher drain, exit 0); SIGKILL only if
        the drain window is exhausted.  Returns the exit code."""
        backend.close()
        if proc.poll() is not None:
            return proc.returncode
        proc.send_signal(signal.SIGTERM)
        try:
            return proc.wait(timeout=drain_timeout_s + 10.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            return proc.wait(timeout=10)


class Autoscaler:
    """The tick-driven scale state machine (module docstring).

    ``spawn(index) -> (backend, handle)`` and ``retire(backend,
    handle)`` default to a :class:`ServeLauncher`'s; ``sample_fn``
    defaults to :func:`router_sample`.  All three are injectable so
    the hysteresis logic is testable without processes."""

    def __init__(self, router, *, launcher: ServeLauncher | None = None,
                 spawn=None, retire=None,
                 min_backends: int = 1, max_backends: int = 4,
                 interval_s: float = 5.0,
                 objective: str = "availability", target: float = 0.999,
                 threshold_ms: float | None = None,
                 max_burn_rate: float = 2.0, min_events: int = 5,
                 breach_windows: int = 2, idle_windows: int = 6,
                 idle_rps: float = 0.5, cooldown_s: float = 30.0,
                 drain_timeout_s: float = 20.0,
                 crash_loop_threshold: int = 3,
                 crash_loop_window_s: float = 60.0,
                 sample_fn=None, clock=time.monotonic,
                 statestore=None):
        if int(min_backends) < 1:
            raise ValueError(f"min_backends must be >= 1, "
                             f"got {min_backends!r}")
        if int(max_backends) < int(min_backends):
            raise ValueError(f"max_backends ({max_backends}) must be "
                             f">= min_backends ({min_backends})")
        if objective not in sloengine.OBJECTIVES:
            raise ValueError(f"objective {objective!r}; expected one "
                             f"of {sloengine.OBJECTIVES}")
        if objective == "latency" and threshold_ms is None:
            raise ValueError("a latency-objective autoscaler needs "
                             "threshold_ms")
        self.router = router
        self.launcher = launcher
        self._spawn = spawn if spawn is not None else (
            launcher.spawn if launcher is not None else None)
        self._retire = retire if retire is not None else (
            (lambda b, p: launcher.retire(
                b, p, drain_timeout_s=drain_timeout_s))
            if launcher is not None else None)
        self.min_backends = int(min_backends)
        self.max_backends = int(max_backends)
        self.interval_s = float(interval_s)
        self.objective = objective
        self.budget = 1.0 - float(target)
        self.threshold_ms = threshold_ms
        self.max_burn_rate = float(max_burn_rate)
        self.min_events = int(min_events)
        self.breach_windows = max(1, int(breach_windows))
        self.idle_windows = max(1, int(idle_windows))
        self.idle_rps = float(idle_rps)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._sample_fn = sample_fn if sample_fn is not None \
            else router_sample
        self._clock = clock
        self.statestore = statestore
        self._lock = threading.Lock()
        self._managed: list[tuple] = []       # (backend, handle), LIFO
        self._spawned = 0
        self._prev: SLOSample | None = None
        self._hot = 0
        self._idle = 0
        self._cooldown_until: float | None = None
        self._last = {"burn_rate": 0.0, "request_rate": 0.0,
                      "events": 0.0}
        self._scale_outs = 0
        self._scale_ins = 0
        self._last_error: str | None = None
        self.crash_loop_threshold = max(1, int(crash_loop_threshold))
        self.crash_loop_window_s = float(crash_loop_window_s)
        self._boot_failures: collections.deque = collections.deque()
        self._crash_looping = False
        #: optional hook (HA coordinator's note_fenced) called when a
        #: boot/drain is refused by epoch fencing — the demotion runs
        #: on the coordinator's thread, never inline in a tick
        self.on_fenced = None
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None

    # -- membership bookkeeping -------------------------------------------
    def adopt(self, backend, handle, *,
              journal: str | None = "boot") -> None:
        """Track an already-booted backend as managed (the CLI boots
        the min-floor before the router exists, then adopts here; the
        reconcile path re-adopts journaled survivors with
        ``journal="adopt"``).  The index counter advances past the
        adopted name so a later spawn can never collide with it."""
        m = re.fullmatch(r"as(\d+)", str(backend.name))
        with self._lock:
            self._managed.append((backend, handle))
            if m:
                self._spawned = max(self._spawned, int(m.group(1)) + 1)
            else:
                self._spawned += 1
        if journal:
            self._journal_child(journal, backend, handle)

    def managed_names(self) -> list[str]:
        with self._lock:
            return [b.name for b, _h in self._managed]

    def next_index(self) -> int:
        """Claim the next never-used boot index (→ backend ``asN``)."""
        with self._lock:
            self._spawned += 1
            return self._spawned - 1

    def _journal_child(self, kind: str, backend, handle) -> None:
        """Durably record one managed-child mutation (boot / adopt /
        drain) so a restarted router can reconcile instead of
        re-booting.  Journal trouble is reported, never raised — the
        child is already running (or already gone); bookkeeping must
        not take the fleet down with it."""
        if self.statestore is None:
            return
        pid = getattr(handle, "pid", None)
        fields = {"backend": backend.name, "pid": pid}
        if kind != "drain":
            try:
                fields["port"] = urlsplit(backend.url).port
            except ValueError:
                fields["port"] = None
            fields["url"] = backend.url
            fields["args"] = (list(self.launcher.serve_args)
                              if self.launcher is not None else [])
            fields["identity"] = (getattr(handle, "identity", None)
                                  or (process_identity(pid)
                                      if pid else None))
        try:
            self.statestore.append(kind, **fields)
        except FencedError as e:
            # the action already happened; record the deposition and
            # let the coordinator demote us from ITS thread
            self._last_error = str(e)
            self._note_fenced()
        except OSError as e:
            self._last_error = f"journal append failed: {e}"

    # -- epoch fencing ------------------------------------------------------
    def _note_fenced(self) -> None:
        if self.on_fenced is not None:
            try:
                self.on_fenced()
            except Exception:
                pass

    def _fenced(self, action: str) -> bool:
        """True when leased HA says a newer epoch owns the fleet — a
        deposed primary must not boot or drain anything (the new
        primary owns those children now).  Counts the refusal and
        pokes the coordinator to demote us."""
        if self.statestore is None or not self.statestore.fenced():
            return False
        _fenced_mutations.inc(action=action)
        self._last_error = (f"{action} refused: writer epoch "
                            f"{self.statestore.writer_epoch} fenced "
                            f"by a newer leadership epoch")
        self._note_fenced()
        return True

    # -- crash-loop fail-fast -----------------------------------------------
    def _note_boot_failure(self, now: float, name: str,
                           error: Exception) -> None:
        """One failed boot; trip the sticky fail-fast when
        ``crash_loop_threshold`` of them land inside
        ``crash_loop_window_s``."""
        self._boot_failures.append(now)
        while self._boot_failures and \
                now - self._boot_failures[0] > self.crash_loop_window_s:
            self._boot_failures.popleft()
        if len(self._boot_failures) < self.crash_loop_threshold \
                or self._crash_looping:
            return
        self._crash_looping = True
        _crash_loops.inc()
        print(f"autoscale: CRASH LOOP — "
              f"{len(self._boot_failures)} boot failures within "
              f"{self.crash_loop_window_s:g}s (last: {error}); "
              f"stopping the boot loop until an operator intervenes",
              flush=True)
        tail = (self.launcher.log_tail(name)
                if self.launcher is not None else None)
        if tail:
            print(f"autoscale: log tail of failing child {name}:\n"
                  f"{tail}", flush=True)

    # -- the state machine -------------------------------------------------
    def tick(self, now: float | None = None) -> dict:
        """One sampling window: measure burn + request rate, advance
        the hysteresis counters, maybe act.  Never raises — a failed
        boot/drain is recorded in ``last_error`` and retried on a
        later tick (the loop must outlive one bad action)."""
        now = self._clock() if now is None else now
        sample = self._sample_fn()
        prev, self._prev = self._prev, sample
        burn = rate = events = 0.0
        if prev is not None:
            burn, events = sloengine.burn_between(
                prev, sample, budget=self.budget,
                objective=self.objective,
                threshold_ms=self.threshold_ms,
                min_events=self.min_events)
            dt = max(1e-9, sample.at - prev.at)
            rate = max(0.0, sample.requests - prev.requests) / dt
        hot = prev is not None and burn >= self.max_burn_rate
        idle = prev is not None and not hot and rate < self.idle_rps
        self._hot = self._hot + 1 if hot else 0
        self._idle = self._idle + 1 if idle else 0
        _burn_g.set(burn)
        self._last = {"burn_rate": round(burn, 4),
                      "request_rate": round(rate, 3),
                      "events": events}
        action = None
        cooling = (self._cooldown_until is not None
                   and now < self._cooldown_until)
        total = self.router.backend_count()
        if not cooling:
            if self._hot >= self.breach_windows \
                    and total < self.max_backends:
                action = self._scale_out(now)
            elif self._idle >= self.idle_windows \
                    and total > self.min_backends \
                    and self.managed_names():
                action = self._scale_in(now)
        _backends_g.set(float(self.router.backend_count()))
        return {"action": action, **self.status()}

    def _acted(self, now: float) -> None:
        self._hot = self._idle = 0
        self._cooldown_until = now + self.cooldown_s

    def _scale_out(self, now: float) -> str | None:
        if self._spawn is None:
            self._last_error = "no spawn path configured"
            return None
        if self._crash_looping:
            self._last_error = ("crash loop: boot loop stopped "
                                "(see log tail above)")
            return None
        if self._fenced("boot"):
            return None
        idx = self.next_index()
        try:
            backend, handle = self._spawn(idx)
        except Exception as e:
            self._last_error = f"scale-out failed: {e}"
            self._note_boot_failure(now, f"as{idx}", e)
            self._acted(now)   # cooldown anyway: don't hammer boots
            return None
        try:
            self.router.add_backend(backend)
        except Exception as e:
            self._last_error = f"add_backend failed: {e}"
            if self._retire is not None:
                try:
                    self._retire(backend, handle)
                except Exception:
                    pass
            self._acted(now)
            return None
        with self._lock:
            self._managed.append((backend, handle))
        self._journal_child("boot", backend, handle)
        self._scale_outs += 1
        self._last_error = None
        _events.inc(direction="out")
        self._acted(now)
        return f"scale_out:{backend.name}"

    def _scale_in(self, now: float) -> str | None:
        if self._fenced("drain"):
            return None
        with self._lock:
            if not self._managed:
                return None
            backend, handle = self._managed.pop()
        try:
            self.router.remove_backend(backend.name)
        except Exception as e:
            self._last_error = f"remove_backend failed: {e}"
        try:
            if self._retire is not None:
                self._retire(backend, handle)
        except Exception as e:
            self._last_error = f"scale-in drain failed: {e}"
            self._acted(now)
            return None
        self._journal_child("drain", backend, handle)
        self._scale_ins += 1
        self._last_error = None
        _events.inc(direction="in")
        self._acted(now)
        return f"scale_in:{backend.name}"

    # -- surfaces ----------------------------------------------------------
    def status(self) -> dict:
        now = self._clock()
        cooldown = (max(0.0, self._cooldown_until - now)
                    if self._cooldown_until is not None else 0.0)
        return {"backends": self.router.backend_count(),
                "min_backends": self.min_backends,
                "max_backends": self.max_backends,
                "managed": self.managed_names(),
                "burn_rate": self._last["burn_rate"],
                "request_rate": self._last["request_rate"],
                "hot_windows": self._hot,
                "idle_windows": self._idle,
                "cooldown_remaining_s": round(cooldown, 1),
                "scale_outs": self._scale_outs,
                "scale_ins": self._scale_ins,
                "crash_looping": self._crash_looping,
                "last_error": self._last_error}

    # -- lifecycle ---------------------------------------------------------
    def _run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:     # the loop must survive a tick
                self._last_error = f"tick failed: {e}"

    def start(self) -> "Autoscaler":
        # clear, don't assume fresh: a standby promotion restarts the
        # loop after a demotion's stop() set the event
        self._stop_event.clear()
        self.router.attach_autoscaler(self.status)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="znicz-fleet-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(5.0)

    def shutdown(self, teardown: bool = True) -> None:
        """Stop the loop; with ``teardown`` drain EVERY managed
        backend (the CLI's SIGTERM path — the router's static floor
        is left alone).  ``teardown=False`` is journal-and-keep: the
        children stay up, their boot/adopt records stay in the
        journal, and the next ``route --state-dir`` re-adopts them
        instead of re-booting (docs/fleet.md).  A FENCED shutdown
        always keeps the children: a newer epoch owns them, and
        draining them out from under the new primary would be the
        double-drain this fencing exists to prevent."""
        self.stop()
        if teardown and self.statestore is not None \
                and self.statestore.fenced():
            _fenced_mutations.inc(action="drain")
            print("autoscale: shutdown fenced by a newer leadership "
                  "epoch — keeping children for the new primary",
                  flush=True)
            teardown = False
        if not teardown:
            return
        while True:
            with self._lock:
                if not self._managed:
                    return
                backend, handle = self._managed.pop()
            try:
                self.router.remove_backend(backend.name)
            except Exception:
                pass
            try:
                if self._retire is not None:
                    self._retire(backend, handle)
            except Exception as e:
                self._last_error = f"shutdown drain failed: {e}"
            self._journal_child("drain", backend, handle)


def reconcile_children(router, scaler: Autoscaler,
                       launcher: ServeLauncher, children: dict, *,
                       deadline_s: float = 30.0,
                       poll_interval_s: float = 0.2) -> dict:
    """Reconcile journaled autoscaler children after a router restart:
    re-adopt instead of re-boot, drain instead of leak.

    ``children`` is :attr:`~znicz_tpu.fleet.statestore
    .ControlPlaneState.children` — the journal's live boot/adopt
    records.  Each child gets one verdict (the
    ``backend_adopted_total{outcome}`` vocabulary):

    * ``adopted`` — pid alive, identity matches, boot args match this
      router's ``--serve-arg`` generation, AND healthz + a real
      ``/predict`` canary both answer → re-enters rotation in place,
      zero double-boot.
    * ``dead`` — nothing wears the pid; the record is drained away.
    * ``stale_pid`` — the pid is alive but its kernel start-time
      identity differs: an unrelated process recycled the number.
      NEVER signalled; drained from the journal and replaced.
    * ``stale_args`` — alive, ours, but booted under different serve
      args (unknown generation): drained via SIGTERM and replaced.
    * ``replaced`` — alive but half-dead (healthz or the predict
      canary refused within its slice of ``deadline_s``): drained.
    * ``invalid`` — the record lacks a pid/url to act on.

    Every wait in here is bounded — ``deadline_s`` is split across
    the children so a wedged child cannot stall the whole
    reconciliation past the router's advertised Retry-After."""
    outcomes: dict[str, int] = {}

    def verdict(name: str, outcome: str, detail: str = "") -> None:
        outcomes[outcome] = outcomes.get(outcome, 0) + 1
        _backend_adopted.inc(outcome=outcome)
        extra = f" ({detail})" if detail else ""
        print(f"reconcile: child {name}: {outcome}{extra}", flush=True)

    def drain_record(name: str) -> None:
        if scaler.statestore is None:
            return
        try:
            scaler.statestore.append("drain", backend=name,
                                     source="reconcile")
        except OSError:
            pass

    per = max(2.0, float(deadline_s) / max(1, len(children)))
    probe_timeout = min(5.0, per)
    want_args = list(launcher.serve_args)
    for name, rec in sorted(children.items()):
        pid, url = rec.get("pid"), rec.get("url")
        if not pid or not url:
            drain_record(name)
            verdict(name, "invalid", "journal record lacks pid/url")
            continue
        pid = int(pid)
        if not pid_alive(pid):
            drain_record(name)
            verdict(name, "dead", f"pid {pid} gone")
            continue
        recorded = rec.get("identity")
        live = process_identity(pid)
        if recorded is not None and live != recorded:
            # recycled pid: an unrelated process wears the number now —
            # treat the child as dead and do not signal anyone
            drain_record(name)
            verdict(name, "stale_pid",
                    f"pid {pid} identity {live} != recorded {recorded}")
            continue
        handle = OrphanProcess(pid, recorded or live)
        backend = Backend(
            str(url), name=str(name),
            timeout_s=launcher.forward_timeout_s,
            breaker=CircuitBreaker(
                failure_threshold=launcher.breaker_threshold,
                cooldown_s=launcher.breaker_cooldown_s))
        if list(rec.get("args") or []) != want_args:
            try:
                launcher.retire(backend, handle, drain_timeout_s=per)
            except Exception:
                pass
            drain_record(name)
            verdict(name, "stale_args",
                    "booted under a different serve-arg generation")
            continue
        # alive and the right generation: healthz AND a predict canary
        # must both answer before it re-enters rotation — a pid that
        # exists but serves nothing is half-dead, not adopted
        healthy = False
        deadline = time.monotonic() + per
        while time.monotonic() < deadline:
            try:
                if backend.canary("GET", "/healthz", None, {},
                                  timeout_s=probe_timeout) == 200:
                    healthy = True
                    break
            except BackendDown:
                pass
            if handle.poll() is not None:
                break
            time.sleep(poll_interval_s)
        answered = False
        if healthy:
            try:
                backend.canary("POST", "/predict", b'{"inputs": []}',
                               {"Content-Type": "application/json"},
                               timeout_s=probe_timeout)
                answered = True   # ANY status: the predict path answers
            except BackendDown:
                answered = False
        if not (healthy and answered):
            try:
                launcher.retire(backend, handle, drain_timeout_s=per)
            except Exception:
                pass
            drain_record(name)
            verdict(name, "replaced",
                    "healthz" if not healthy else "predict canary")
            continue
        try:
            router.add_backend(backend)
        except Exception as e:
            drain_record(name)
            verdict(name, "invalid", f"add_backend: {e}")
            continue
        scaler.adopt(backend, handle, journal="adopt")
        verdict(name, "adopted", f"pid {pid} re-adopted in place")
    return outcomes


def main(argv=None) -> int:
    """``python -m znicz_tpu autoscale`` — the route CLI with
    ``--autoscale`` pre-set (one flag namespace, documented on
    ``route --help``)."""
    from .router import main as route_main
    args = list(sys.argv[1:]) if argv is None else list(argv)
    if "--autoscale" not in args:
        args = args + ["--autoscale"]
    return route_main(args)
