"""Promote-one-then-fleet: rolling deployment over the router tier.

PR 6's :class:`~znicz_tpu.promotion.controller.PromotionController`
drives ONE target through verify → export → canary → SLO watch.  This
module is the fleet-shaped target that plugs into it unchanged:

* :meth:`FleetTarget.reload` canaries the **first** backend only —
  optionally dropping its router weight first (``canary_weight``), so
  the candidate generation sees a controlled slice of live traffic
  (0.0 = a *dark* canary that serves no router traffic during the
  watch; judgment then happens on the walk).
* :meth:`FleetTarget.sample` reads the canary backend's ``/metrics``
  — the controller's SLO watch judges the one backend actually
  serving the candidate.
* :meth:`FleetTarget.finalize` is the **fleet walk** the controller
  calls after a clean watch (the duck-typed hook targets may omit):
  restore the canary's weight, then roll the remaining backends one
  at a time — each one's weight is reduced while it swaps and
  settles (weighted traffic splitting), and after each swap the
  fleet-aggregated burn rate (PR 12's
  :class:`~znicz_tpu.promotion.slo.BurnRatePolicy` arithmetic over
  the SUM of every backend's sample) is re-judged.  A mid-walk breach
  rolls every already-walked backend — canary included — back to the
  previous artifact and restores weights: the fleet converges, it
  never wedges half-rolled.

Generation skew is tolerated by construction: mid-walk the fleet
serves MIXED generations (each backend answers from its own
consistent generation — the router holds no response cache, so a new
generation can never serve a predecessor's bytes), and the post-roll
invariant is byte-identical outputs across every backend
(``chaos --scenario fleet`` pins both).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from ..promotion.controller import HttpTarget
from ..promotion.slo import BurnRatePolicy, SLOSample


def _as_url_list(router_url) -> list:
    """Normalize ``router_url`` (None | str | iterable of str) to a
    trailing-slash url list — the HA client contract: a fleet fronted
    by a primary + hot standbys is addressed by ALL router urls, and
    callers fail over on transport error."""
    if router_url is None:
        return []
    if isinstance(router_url, str):
        router_url = [router_url]
    return [u if u.endswith("/") else u + "/" for u in router_url]


def merge_samples(samples) -> SLOSample:
    """Sum N backends' :class:`SLOSample` s into one fleet sample:
    cumulative bucket counts, request and 5xx counters all add;
    ``breaker_state`` keeps the WORST state across the fleet (one
    open engine breaker is a fleet-level signal)."""
    rank = {None: 0, "closed": 1, "half_open": 2, "open": 3}
    latency_cum: dict = {}
    count = requests = errors = 0.0
    worst = None
    for s in samples:
        for edge, v in s.latency_cum.items():
            latency_cum[edge] = latency_cum.get(edge, 0.0) + v
        count += s.latency_count
        requests += s.requests
        errors += s.errors_5xx
        if rank.get(s.breaker_state, 0) > rank.get(worst, 0):
            worst = s.breaker_state
    return SLOSample(at=time.time(), latency_cum=latency_cum,
                     latency_count=count, requests=requests,
                     errors_5xx=errors, breaker_state=worst)


class FleetTarget:
    """Promotion target spanning N serve backends behind one router.

    Duck-type-compatible with
    :class:`~znicz_tpu.promotion.controller.HttpTarget` where the
    controller touches a target (``attach``/``reload``/``sample``)
    plus the optional ``finalize`` walk hook.  Backends are driven
    through their own ``/admin/reload`` + ``/metrics`` surfaces; the
    router is only consulted for traffic weights (``POST
    /admin/weight``) — ``router_url=None`` degrades to a walk without
    traffic splitting.

    ``router_url`` accepts one url or a LIST of them (an HA pair:
    primary + hot standbys, fleet/ha.py): requests go to the active
    url and rotate to the next on transport error — an HTTP answer,
    including a standby's 503 + Retry-After, is handled by the
    existing best-effort discipline, not treated as router death."""

    def __init__(self, backend_urls, *, router_url=None,
                 admin_token: str | None = None, timeout_s: float = 60.0,
                 canary_weight: float | None = 0.25,
                 walk_weight: float | None = None,
                 walk_policy: BurnRatePolicy | None = None,
                 settle_s: float = 2.0,
                 probe_interval_s: float = 0.25):
        if not backend_urls:
            raise ValueError("a fleet target needs at least one "
                             "backend url")
        self.urls = [u if u.endswith("/") else u + "/"
                     for u in backend_urls]
        self.router_urls = _as_url_list(router_url)
        self._router_active = 0
        self.admin_token = admin_token
        self.timeout_s = float(timeout_s)
        #: router-weight multiplier for the canarying backend during
        #: the controller's watch (None = leave weights alone;
        #: 0.0 = dark canary — no router traffic until the walk)
        self.canary_weight = canary_weight
        #: weight multiplier for each backend while IT swaps and
        #: settles mid-walk (defaults to canary_weight)
        self.walk_weight = (walk_weight if walk_weight is not None
                            else canary_weight)
        self.walk_policy = (walk_policy if walk_policy is not None
                            else BurnRatePolicy(
                                objective="availability", target=0.999,
                                window_s=60.0, probe_interval_s=0.5,
                                max_burn_rate=2.0, min_samples=5))
        self.settle_s = float(settle_s)
        self.probe_interval_s = float(probe_interval_s)
        self._targets = [HttpTarget(u, admin_token=admin_token,
                                    timeout_s=timeout_s)
                         for u in self.urls]
        #: router backend name + base weight per backend url, fetched
        #: lazily from the router's /healthz (None entries: the
        #: router does not front that url — weights are skipped)
        self._names: dict | None = None
        self._status_lock = threading.Lock()
        self._status = {"state": "idle", "walked": 0,
                        "fleet_size": len(self.urls),
                        "last_outcome": None}

    @property
    def router_url(self) -> str | None:
        """The currently-active router url (the one the last request
        succeeded against); None without a router."""
        if not self.router_urls:
            return None
        return self.router_urls[self._router_active
                                % len(self.router_urls)]

    @classmethod
    def from_router(cls, router_url, **kwargs) -> "FleetTarget":
        """Discover the backend urls from a running router's
        ``/healthz`` and build a target over them (the
        ``promote --fleet`` CLI path).  ``router_url`` may be a list
        (HA pair): discovery tries each in order — any replica's
        /healthz lists the fleet, primary or standby."""
        urls = _as_url_list(router_url)
        last_error: Exception | None = None
        health = None
        for url in urls:
            try:
                with urllib.request.urlopen(url + "healthz",
                                            timeout=30) as r:
                    health = json.loads(r.read())
                break
            except Exception as e:
                last_error = e
        if health is None:
            raise ValueError(f"no router of {urls} answered "
                             f"/healthz: {last_error}")
        rows = health.get("backends") or []
        if not rows:
            raise ValueError(f"router {router_url} reports no "
                             f"backends")
        return cls([row["url"] for row in rows], router_url=urls,
                   **kwargs)

    # -- controller protocol ----------------------------------------------
    def attach(self, status_fn) -> None:
        # the controller's status lives in its own process; a REMOTE
        # router cannot render it (same stance as HttpTarget.attach)
        pass

    def status(self) -> dict:
        """The walk's own status (attachable to an in-process
        router's /healthz via ``router.attach_rollout``)."""
        with self._status_lock:
            return dict(self._status)

    def _set_status(self, **fields) -> None:
        with self._status_lock:
            self._status.update(fields)

    def reload(self, path: str) -> dict:
        """Canary stage: swap the FIRST backend only (weight-reduced
        when the router is known), leaving the rest of the fleet on
        the old generation."""
        self._set_status(state="canarying", walked=0,
                         candidate=path)
        if self.canary_weight is not None:
            self._set_weight(0, self.canary_weight)
        return self._targets[0].reload(path)

    def sample(self):
        """The controller's watch judges the canary backend — the one
        process actually serving the candidate generation."""
        return self._targets[0].sample()

    def conclude(self, outcome: str) -> None:
        """Controller hook, fired once per concluded attempt WHATEVER
        the outcome: restore the canary backend's router weight and
        settle the status.  Without this, any failed outcome —
        canary_failed, a watch breach (whose rollback re-enters
        :meth:`reload` and re-applies the reduction), aborted — would
        leave backend 0 serving at canary weight (0 = fully drained)
        indefinitely.  Idempotent: the clean-walk path has already
        restored it."""
        if self.canary_weight is not None:
            self._set_weight(0, None)
        # a concluded attempt may have shifted residency (reloads
        # page weights in) — ask the router's placement tier to
        # re-score so the map respects the post-walk world (PR 16;
        # no-op on routers without --placement)
        self._request_rebalance()
        self._set_status(state="idle", last_outcome=outcome,
                         walking=None)

    def fleet_sample(self) -> SLOSample:
        """The walk's judgment input: every backend's sample, summed."""
        return merge_samples(t.sample() for t in self._targets)

    # -- the walk ----------------------------------------------------------
    def finalize(self, path: str, previous: str | None = None) -> dict:
        """Walk the remaining backends onto ``path`` after the canary
        watch passed.  Never raises: any failure rolls the walked
        prefix (canary included) back to ``previous`` and reports
        ``{"outcome": "rolled_back" | "rollback_failed", ...}``; a
        complete walk reports ``{"outcome": "ok", "walked": N}``."""
        try:
            return self._finalize(path, previous)
        except Exception as e:       # belt: an unexpected walk crash
            #                          must still try to converge.
            #                          The status tracks walk depth;
            #                          +1 covers a reload that landed
            #                          before the crash was recorded
            depth = min(len(self._targets),
                        int(self.status().get("walked") or 1) + 1)
            rolled = self._roll_back(previous, walked=depth)
            self._set_status(state="idle",
                             last_outcome="rollback_failed"
                             if not rolled else "rolled_back")
            return {"outcome": ("rolled_back" if rolled
                                else "rollback_failed"),
                    "error": f"fleet walk crashed: {e!r}"}
        finally:
            # whatever the walk's outcome, generations and residency
            # moved under the placement map — refresh it (PR 16)
            self._request_rebalance()

    def _start_sample(self) -> SLOSample | None:
        """The walk's baseline, scrape-tolerantly: a transient
        /metrics failure on one backend must not read as a fleet
        incident (the same stance as :meth:`_settle`)."""
        for _attempt in range(3):
            try:
                return self.fleet_sample()
            except Exception:
                time.sleep(self.probe_interval_s)
        return None

    def _finalize(self, path: str, previous: str | None) -> dict:
        self._set_status(state="walking", walked=1)
        if self.canary_weight is not None:
            self._set_weight(0, None)        # canary back to full
        policy = self.walk_policy
        start = self._start_sample()
        if start is None:
            # the fleet cannot be judged at all: the controller's
            # unjudgeable-watch stance applies — roll the CANARY back
            # (the only backend on the candidate; the unwalked rest
            # still serve the previous generation untouched)
            rolled = self._roll_back(previous, walked=1)
            self._set_status(state="idle", walked=0,
                             last_outcome="rolled_back")
            return {"outcome": ("rolled_back" if rolled
                                else "rollback_failed"),
                    "walked": 1,
                    "error": "fleet /metrics unreadable at walk "
                             "start — an unjudgeable candidate must "
                             "not front steady-state traffic"}
        walked = 1                           # the canary is live
        for i in range(1, len(self._targets)):
            self._set_status(walked=walked,
                             walking=self.urls[i])
            if self.walk_weight is not None:
                self._set_weight(i, self.walk_weight)
            try:
                rec = self._targets[i].reload(path)
            except Exception as e:
                rec = {"outcome": "reload_raised", "error": repr(e)}
            if rec.get("outcome") != "ok":
                rolled = self._roll_back(previous, walked=walked)
                self._set_weight(i, None)
                self._set_status(state="idle", walked=0,
                                 last_outcome="rolled_back")
                return {"outcome": ("rolled_back" if rolled
                                    else "rollback_failed"),
                        "walked": walked,
                        "error": f"backend {i} reload "
                                 f"{rec.get('outcome')}: "
                                 f"{rec.get('error')}"}
            walked += 1
            breaches = self._settle(policy, start)
            self._set_weight(i, None)
            if breaches:
                rolled = self._roll_back(previous, walked=walked)
                self._set_status(state="idle", walked=0,
                                 last_outcome="rolled_back")
                return {"outcome": ("rolled_back" if rolled
                                    else "rollback_failed"),
                        "walked": walked, "breaches": breaches}
        self._set_status(state="idle", walked=walked,
                         last_outcome="ok", walking=None)
        return {"outcome": "ok", "walked": walked}

    def _settle(self, policy, start) -> list:
        """Hold ``settle_s`` after one backend swapped, re-judging the
        fleet-aggregated burn every ``probe_interval_s`` — the
        mid-walk SLO gate.  Returns the breaches (empty = clean)."""
        deadline = time.monotonic() + self.settle_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return []
            time.sleep(min(self.probe_interval_s, remaining))
            try:
                breaches = policy.evaluate(start, self.fleet_sample())
            except Exception:
                # an unreadable scrape is not a breach; the next tick
                # (or the next walk step) re-judges
                continue
            if breaches:
                return breaches

    def _roll_back(self, previous: str | None, walked: int) -> bool:
        """Reload ``previous`` on every backend of the walked prefix
        (newest-swapped first, canary last).  True when every reload
        landed ``ok``; False (rollback_failed) when ``previous`` is
        unknown or any backend refused — the fleet is then mixed and
        the operator owns the next move (the controller ledgers it)."""
        if previous is None:
            return False
        ok = True
        for i in range(min(walked, len(self._targets)) - 1, -1, -1):
            try:
                rec = self._targets[i].reload(previous)
                ok = ok and rec.get("outcome") == "ok"
            except Exception:
                ok = False
        return ok

    # -- router weight control --------------------------------------------
    def _router_request(self, path: str, body: bytes | None = None,
                        headers: dict | None = None) -> bytes:
        """One request against the active router url, failing over
        to the next url on TRANSPORT error only (connection refused,
        reset, timeout).  An HTTP error status is an ANSWER — a
        standby's 503 + Retry-After or a 404 must reach the caller's
        own discipline, not trigger a pointless rotation.  The url
        that answers becomes the new active one.  Raises the last
        transport error when every url is down."""
        last: Exception | None = None
        n = len(self.router_urls)
        for hop in range(n):
            i = (self._router_active + hop) % n
            url = self.router_urls[i]
            req = urllib.request.Request(url + path, body,
                                         headers or {})
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    data = r.read()
                self._router_active = i
                return data
            except urllib.error.HTTPError:
                self._router_active = i
                raise
            except (urllib.error.URLError, OSError) as e:
                last = e
        raise last if last is not None \
            else OSError("no router urls configured")

    def _backend_names(self) -> dict:
        """url -> (router backend name, base weight), fetched once
        from the router's /healthz; {} without a router."""
        if self._names is not None:
            return self._names
        if not self.router_urls:
            self._names = {}
            return self._names
        try:
            health = json.loads(self._router_request("healthz"))
            self._names = {row["url"]: (row["name"], row["weight"])
                           for row in health.get("backends") or []}
        except Exception:
            # do NOT cache the failure: an unreachable router at this
            # instant must not disable traffic splitting for every
            # later walk step
            return {}
        return self._names

    def _set_weight(self, index: int, multiplier: float | None) -> None:
        """Scale backend ``index``'s router weight by ``multiplier``
        of its base (None = restore the base weight).  Best-effort:
        a router that cannot be reached must not fail the promotion —
        the walk still converges, just without traffic splitting."""
        entry = self._backend_names().get(self.urls[index])
        if entry is None:
            return
        name, base = entry
        weight = base if multiplier is None else base * multiplier
        body = json.dumps({"backend": name,
                           "weight": weight}).encode()
        headers = {"Content-Type": "application/json"}
        if self.admin_token is not None:
            headers["X-Admin-Token"] = self.admin_token
        try:
            self._router_request("admin/weight", body, headers)
        except Exception:
            pass

    def _request_rebalance(self) -> None:
        """Ask the router's placement tier to re-score (``POST
        /admin/placement {"action": "rebalance"}``).  Best-effort,
        like :meth:`_set_weight`: no router, a router without
        ``--placement`` (404), or a transient refusal must not fail
        the promotion — the prober's discovery recompute converges
        the map anyway, just later."""
        if not self.router_urls:
            return
        body = json.dumps({"action": "rebalance"}).encode()
        headers = {"Content-Type": "application/json"}
        if self.admin_token is not None:
            headers["X-Admin-Token"] = self.admin_token
        try:
            self._router_request("admin/placement", body, headers)
        except Exception:
            pass
