"""Placement engine: the router decides where models live.

PR 14's router spreads every request over N identical backends, so
fleet weight footprint is N × the whole zoo and each backend's
weight-residency LRU (PR 11) thrashes identically.  This module is
the paper's master-side scheduling instinct (the VELES master decides
*where work lives*, not just how to fan it out) rebuilt for the
serving fleet: each registry entry — the routable unit — is assigned
to a scored **subset** of backends, and the router only routes a
tenant inside its subset.

* **Weighted rendezvous (HRW) assignment** — for every (model,
  backend) pair a deterministic hash draw is scaled by the pair's
  score and the lowest ``replication`` draws win.  Rendezvous hashing
  is what makes the assignment *consistent*: a backend joining or
  leaving only moves the tenants that ranked it, never reshuffles the
  fleet — a tenant's memo/executable caches stay warm across
  membership churn (cache affinity).
* **Residency-/load-aware scoring** — the score multiplies an
  affinity boost for backends already holding the tenant's device
  weights (the ``model_resident{model}`` signal, read from the
  healthz rows the prober already caches) by a busy penalty derived
  from the backend's device-time burn rate (the
  ``model_device_ms_total{model}`` / ``engine_busy_ratio`` lineage).
  Residency boosting is deliberately self-reinforcing: once placed
  and paged in, a tenant stays put until a pin, a departure, or a
  large load skew moves it.
* **Replication factor** — each tenant lives on ``replication``
  backends (primary first), so fleet resident bytes converge to
  ~replication × the zoo instead of N ×; the chaos ``placement``
  drill pins the ≤ (1 + replication) × bound (the slack is one
  in-transition copy).
* **Pins** — ``POST /admin/placement`` can pin a tenant to explicit
  backends; pins survive recomputes and beat scoring.

The engine is pure policy: it owns no HTTP and no sockets.  The
router feeds it candidates (name, residency set, busy ratio), applies
the returned map on the request path, and pushes per-backend
placement *hints* down to each zoo's eviction pass
(``ModelZoo.set_placement_hint``) so the footprint bound is enforced,
not hoped for.  Families: ``placement_generation``,
``placement_models``, ``placement_rebalance_total{cause}``,
``placement_moves_total``, ``placement_degraded_total{model}``
(docs/observability.md).
"""

from __future__ import annotations

import hashlib
import math
import threading
import time

from ..telemetry.registry import REGISTRY

_generation_g = REGISTRY.gauge(
    "placement_generation",
    "ordinal of the placement map currently enforced by the router "
    "(bumps on every recompute — rebalance, membership change, pin)")
_models_g = REGISTRY.gauge(
    "placement_models",
    "tenants the current placement map assigns (models discovered "
    "from backend healthz probes, plus pinned names)")
_rebalances = REGISTRY.counter(
    "placement_rebalance_total",
    "placement recomputes, by cause (admin | join | leave | "
    "discovery | pin)")
_moves = REGISTRY.counter(
    "placement_moves_total",
    "tenants whose placed backend set changed across a recompute — "
    "each move is a cold memo/executable cache somewhere, so a noisy "
    "series here means the scoring is churning")
_degraded = REGISTRY.counter(
    "placement_degraded_total",
    "requests the router had to route OUTSIDE the tenant's placement "
    "set because no placed backend could take them (degrade-to-any-"
    "healthy, never refuse), by model")


def note_degraded(model: str | None) -> None:
    """Count one routed-outside-the-set request (the router's pick
    loop calls this; bounded label set — zoo names plus _default)."""
    _degraded.inc(model=model or "_default")


class PlacementCandidate:
    """One backend as the scorer sees it: its name, the tenants whose
    device weights it currently holds (residency affinity), and its
    busy ratio (device-time burn fraction, [0, 1]-ish)."""

    __slots__ = ("name", "resident", "busy")

    def __init__(self, name: str, *, resident=(), busy: float = 0.0):
        self.name = str(name)
        self.resident = frozenset(resident)
        self.busy = max(0.0, float(busy))


def _draw(model: str, backend: str) -> float:
    """Deterministic uniform draw in (0, 1) for one (model, backend)
    pair — blake2b, not ``hash()``: placement must agree across
    processes and PYTHONHASHSEED."""
    h = hashlib.blake2b(f"{model}\x00{backend}".encode(),
                        digest_size=8).digest()
    return (int.from_bytes(h, "big") + 1) / (2.0 ** 64 + 2)


def score_weight(model: str, cand: PlacementCandidate, *,
                 affinity_boost: float = 4.0,
                 busy_penalty: float = 1.0) -> float:
    """The (model, backend) score the rendezvous draw is scaled by:
    > 0 always (a busy backend is dispreferred, never excluded —
    exclusion is the breaker's job, at request time)."""
    w = affinity_boost if model in cand.resident else 1.0
    return w / (1.0 + busy_penalty * cand.busy)


def rank_backends(model: str, candidates, *,
                  affinity_boost: float = 4.0,
                  busy_penalty: float = 1.0) -> list[str]:
    """Every candidate name ranked best-first for ``model`` by
    weighted rendezvous: key = -ln(draw)/weight, lowest wins (the
    classic WRH construction — E[share] proportional to weight,
    deterministic given the inputs)."""
    keyed = []
    for cand in candidates:
        w = score_weight(model, cand, affinity_boost=affinity_boost,
                         busy_penalty=busy_penalty)
        keyed.append((-math.log(_draw(model, cand.name)) / w,
                      cand.name))
    return [name for _k, name in sorted(keyed)]


class PlacementEngine:
    """Scoring + assignment state (pure policy; the router enforces).

    ``plan()`` recomputes the full map; the engine tracks the plan
    generation, the move count against the previous map, and the pin
    table.  Thread-safe: the router recomputes from admin handlers,
    the prober thread, and membership changes."""

    def __init__(self, replication: int = 1, *,
                 affinity_boost: float = 4.0,
                 busy_penalty: float = 1.0):
        if int(replication) < 1:
            raise ValueError(f"replication must be >= 1, "
                             f"got {replication!r}")
        self.replication = int(replication)
        self.affinity_boost = float(affinity_boost)
        self.busy_penalty = float(busy_penalty)
        self._lock = threading.Lock()
        self._pins: dict[str, tuple[str, ...]] = {}
        self._map: dict[str, tuple[str, ...]] = {}
        self._generation = 0
        self._last_cause: str | None = None
        self._moves_total = 0
        self._computed_at: float | None = None

    # -- pins --------------------------------------------------------------
    def pin(self, model: str, backends) -> None:
        """Pin ``model`` to an explicit backend list (beats scoring,
        survives recomputes); ``backends=None`` clears the pin."""
        with self._lock:
            if backends is None:
                self._pins.pop(model, None)
            else:
                names = tuple(str(b) for b in backends)
                if not names:
                    raise ValueError("a pin needs at least one "
                                     "backend (null clears the pin)")
                self._pins[model] = names

    def pins(self) -> dict:
        with self._lock:
            return dict(self._pins)

    def restore_pins(self, pins: dict) -> None:
        """Bulk-reinstall journaled pins in one shot (control-plane
        replay on ``route --state-dir``): last-write-wins state from
        :meth:`~znicz_tpu.fleet.statestore.StateStore.replay`, so
        entries replace the pin table rather than merging into it.
        Callers recompute the plan once afterwards — one rebalance
        for the whole replay, not one per journaled pin."""
        with self._lock:
            self._pins = {str(m): tuple(str(b) for b in names)
                          for m, names in pins.items() if names}

    # -- the plan ----------------------------------------------------------
    def plan(self, models, candidates, *, cause: str = "manual") -> dict:
        """Assign every model to its top-``replication`` backends.

        ``models``: iterable of tenant names (the union the router
        discovered from backend healthz probes); ``candidates``:
        :class:`PlacementCandidate` s for the current membership.
        Returns the new plan (also retained for :meth:`assignments` /
        :meth:`status`); an empty candidate list yields an empty map
        — the router then routes unrestricted, which is the honest
        degradation."""
        cands = list(candidates)
        with self._lock:
            pins = dict(self._pins)
            previous = dict(self._map)
        new: dict[str, tuple[str, ...]] = {}
        if cands:
            take = min(self.replication, len(cands))
            for model in sorted(set(models) | set(pins)):
                pinned = pins.get(model)
                if pinned:
                    # a pin names backends verbatim — entries naming a
                    # departed backend are kept (the pin is the
                    # operator's intent) but enforcement skips them
                    # via the healthy-membership filter at pick time
                    new[model] = pinned
                else:
                    ranked = rank_backends(
                        model, cands,
                        affinity_boost=self.affinity_boost,
                        busy_penalty=self.busy_penalty)
                    new[model] = tuple(ranked[:take])
        moved = sorted(m for m in set(previous) | set(new)
                       if set(previous.get(m, ()))
                       != set(new.get(m, ())))
        with self._lock:
            self._map = new
            self._generation += 1
            self._last_cause = cause
            self._moves_total += len(moved)
            self._computed_at = time.time()
            gen = self._generation
        _rebalances.inc(cause=cause)
        if moved:
            _moves.inc(len(moved))
        _generation_g.set(float(gen))
        _models_g.set(float(len(new)))
        return {"generation": gen, "cause": cause,
                "assignments": {m: list(v) for m, v in new.items()},
                "moved": moved, "replication": self.replication}

    def assignments(self) -> dict[str, tuple[str, ...]]:
        with self._lock:
            return dict(self._map)

    def placed(self, model: str | None) -> tuple[str, ...]:
        """The backend names ``model`` is placed on (empty tuple =
        unplaced: route anywhere, that is not a degradation)."""
        if model is None:
            return ()
        with self._lock:
            return self._map.get(model, ())

    def backend_models(self, backend: str) -> list[str]:
        """The tenants placed on one backend — the eviction hint the
        router pushes down to that backend's zoo."""
        with self._lock:
            return sorted(m for m, names in self._map.items()
                          if backend in names)

    def status(self) -> dict:
        with self._lock:
            return {
                "replication": self.replication,
                "generation": self._generation,
                "assignments": {m: list(v)
                                for m, v in sorted(self._map.items())},
                "pins": {m: list(v)
                         for m, v in sorted(self._pins.items())},
                "last_cause": self._last_cause,
                "moves_total": self._moves_total,
                "computed_at": self._computed_at}
