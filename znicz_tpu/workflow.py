"""Workflow: unit container + run loop.

Capability parity with the reference's ``veles/workflow.py`` (mount empty —
surveyed contract, SURVEY.md §2.1/§3.1): ``Workflow`` owns units,
``StartPoint`` / ``EndPoint`` delimit the control graph, ``run()`` drives the
dataflow loop (one tick = one minibatch), ``initialize()`` binds devices,
``generate_graph()`` emits DOT, and a per-unit time table is available after
a run (SURVEY.md §5 tracing).

Scheduler semantics (reconstructed reference behaviour): a unit fires in a
tick once ALL its forward-edge parents have fired; ``gate_block`` stops both
the unit and flow through it; ``gate_skip`` passes flow without running.
Loop back-edges (e.g. Decision → Loader) are detected at initialize time and
excluded from the within-tick AND; they are what makes the tick loop iterate.
The loop ends when ``EndPoint`` fires (Decision drops its block when
training completes).

TPU-first: ticks are host-side Python; everything heavy inside a tick is a
jitted XLA call (per-unit, or one fused step via StandardWorkflow).
"""

from __future__ import annotations

from .backends import Device
from .units import Container, Unit


class StartPoint(Unit):
    """Control-flow source (reference parity)."""


class EndPoint(Unit):
    """Control-flow sink; firing it ends the run loop (reference parity)."""


class Workflow(Container):
    """Unit container with the dataflow run loop."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.start_point = StartPoint(self, name="start_point")
        self.end_point = EndPoint(self, name="end_point")
        self.device: Device | None = None
        self._topo: list[Unit] | None = None
        self.stopped = False

    # -- graph analysis ----------------------------------------------------
    def _compute_topology(self) -> None:
        """Classify edges by DFS from start_point (an edge to a node on the
        current DFS stack is a loop back-edge), then Kahn-topo-sort the
        remaining DAG.  Back-edges are excluded from within-tick firing
        conditions; they are what makes the tick loop iterate."""
        back: set[tuple[Unit, Unit]] = set()
        visited: set[Unit] = set()
        on_stack: set[Unit] = set()
        stack: list[tuple[Unit, int]] = [(self.start_point, 0)]
        visited.add(self.start_point)
        on_stack.add(self.start_point)
        while stack:
            u, i = stack[-1]
            if i < len(u._children):
                stack[-1] = (u, i + 1)
                c = u._children[i]
                if c in on_stack:
                    back.add((u, c))
                elif c not in visited:
                    visited.add(c)
                    on_stack.add(c)
                    stack.append((c, 0))
            else:
                stack.pop()
                on_stack.discard(u)
        for u in visited:
            u._fwd_parents = [p for p in u._parents
                              if p in visited and (p, u) not in back]
        # Kahn over forward edges only
        indeg = {u: len(u._fwd_parents) for u in visited}
        ready = [u for u in visited if indeg[u] == 0]
        order: list[Unit] = []
        while ready:
            u = ready.pop()
            order.append(u)
            for c in u._children:
                if c in visited and (u, c) not in back:
                    indeg[c] -= 1
                    if indeg[c] == 0:
                        ready.append(c)
        if len(order) != len(visited):
            raise RuntimeError(
                f"workflow {self.name}: control graph has a cycle not "
                f"broken by a back-edge from start_point's DFS")
        self._topo = order

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, device: Device | None = None, **kwargs) -> None:
        self.device = device if device is not None else Device.create("auto")
        self._compute_topology()
        for u in self._topo:
            if u is not self and not u.initialized:
                u.initialize(device=self.device, **kwargs)
        # Data-only units (consumed via link_attrs, no control edge) still
        # need their resources bound.
        for u in self.units:
            if u is not self and not u.initialized:
                u.initialize(device=self.device, **kwargs)
        self.initialized = True

    def run_tick(self) -> set[Unit]:
        """One pass of the dataflow graph (= one minibatch in training)."""
        fired: set[Unit] = set()
        for u in self._topo:
            parents = u._fwd_parents
            if parents and not all(p in fired for p in parents):
                continue
            if bool(u.gate_block):
                continue
            if not bool(u.gate_skip):
                u.run_timed()
            fired.add(u)
            if self.stopped:
                break
        return fired

    def run(self, max_ticks: int | None = None) -> None:
        if not self.initialized:
            self.initialize()
        self.stopped = False
        ticks = 0
        while not self.stopped:
            fired = self.run_tick()
            ticks += 1
            if self.end_point in fired:
                break
            if max_ticks is not None and ticks >= max_ticks:
                break
            if len(fired) <= 1:   # only start_point fired: graph is stuck
                raise RuntimeError(
                    f"workflow {self.name} deadlocked after {ticks} ticks: "
                    f"no unit past start_point can fire")
        self.stop()

    def stop(self) -> None:
        self.stopped = True
        for u in self.units:
            if u is not self:
                u.stop()

    # -- introspection -----------------------------------------------------
    def time_table(self) -> list[tuple[str, int, float]]:
        """(name, run_count, seconds) per unit, slowest first
        (reference: time-per-unit dump, SURVEY.md §5)."""
        rows = [(u.name, u.run_count, u.time_spent) for u in self.units]
        return sorted(rows, key=lambda r: -r[2])

    def generate_graph(self) -> str:
        """DOT control-graph text (reference generate_graph parity)."""
        lines = [f'digraph "{self.name}" {{']
        for u in self.units:
            for c in u._children:
                lines.append(f'  "{u.name}" -> "{c.name}";')
        lines.append("}")
        return "\n".join(lines)
