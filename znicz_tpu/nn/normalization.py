"""Local-response normalization units (AlexNet LRN).

Parity target: the reference ``veles/znicz/normalization.py`` (mount empty
— surveyed contract, SURVEY.md §2.2 [baseline Normalization (LRN)]):
``LRNormalizerForward`` / ``LRNormalizerBackward`` over a cross-channel
window, with the reference defaults n=5, α=1e-4, β=0.75, k=2.

TPU-first: channels are the minor (lane) axis, so the windowed channel sum
is a cumsum difference — one VPU pass (``ops.normalization``); the forward
caches the denominator tensor for the hand-written backward."""

from __future__ import annotations

import numpy as np

from ..memory import Vector
from ..ops import normalization as lrn_ops
from .nn_units import Forward, GradientDescentBase


class LRNormalizerForward(Forward):
    MAPPING = ("norm", "lrn")

    def __init__(self, workflow=None, name=None, n=5, alpha=1e-4,
                 beta=0.75, k=2.0, **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        self.n, self.alpha, self.beta, self.k = int(n), alpha, beta, k
        self.denom = Vector()

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.output:
            self.output.mem = np.zeros(self.input.shape, np.float32)
        if not self.denom:
            self.denom.mem = np.zeros(self.input.shape, np.float32)
        self.init_vectors(self.output, self.denom)
        n, a, b, k = self.n, self.alpha, self.beta, self.k
        self._fwd_fn = lambda x: lrn_ops.lrn(x, n, a, b, k)

    def numpy_run(self) -> None:
        y, d = lrn_ops.np_lrn(self.input.mem, self.n, self.alpha,
                              self.beta, self.k)
        self.output.mem, self.denom.mem = y, d

    def xla_run(self) -> None:
        y, d = self.jit(self._fwd_fn)(self.input.devmem)
        self.output.devmem, self.denom.devmem = y, d


class LRNormalizerBackward(GradientDescentBase):
    """No parameters — only err_input from the cached denominator."""

    MAPPING = ("norm", "lrn")

    def setup_from_forward(self, fwd) -> "LRNormalizerBackward":
        super().setup_from_forward(fwd)
        self.link_attrs(fwd, "denom")
        self.n, self.alpha, self.beta, self.k = (fwd.n, fwd.alpha,
                                                 fwd.beta, fwd.k)
        self.include_bias = False
        return self

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_input.mem = lrn_ops.np_gd_lrn(
            self.err_output.mem, self.input.mem, self.denom.mem,
            self.n, self.alpha, self.beta, self.k)

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        if not hasattr(self, "_bwd_fn"):
            n, a, b, k = self.n, self.alpha, self.beta, self.k
            self._bwd_fn = self.jit(
                lambda e, x, d: lrn_ops.gd_lrn(e, x, d, n, a, b, k))
        self.err_input.devmem = self._bwd_fn(
            self.err_output.devmem, self.input.devmem, self.denom.devmem)
