"""Tensor slicing / concat glue units for branched nets.

Parity target: the reference ``veles/znicz/cutter.py`` and merger glue
(mount empty — surveyed contract, SURVEY.md §2.2 Cutter/Merger row):
``Cutter`` crops a spatial window out of NHWC activations (``GDCutter``
zero-pads the error back), mergers join branch outputs (channel concat /
elementwise sum) with error-splitting gradients.

TPU-first: all four are pure static-slice/pad/concat ops — XLA folds them
into neighboring kernels, so they cost one fused copy at most."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..memory import Vector
from .nn_units import Forward, GradientDescentBase


class Cutter(Forward):
    """output = input[:, y:y+h, x:x+w, :] (reference Cutter contract)."""

    MAPPING = ("cutter",)

    def __init__(self, workflow=None, name=None, padding=None, **kwargs):
        """``padding`` = (left, top, right, bottom) crop margins — the
        reference's 4-tuple convention."""
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        if padding is None:
            raise ValueError("padding=(left, top, right, bottom) required")
        self.padding = tuple(int(p) for p in padding)

    def output_shape_for(self, x_shape) -> tuple[int, ...]:
        b, h, w, c = x_shape
        le, to, ri, bo = self.padding
        return (b, h - to - bo, w - le - ri, c)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if len(self.input.shape) != 4:
            raise ValueError(f"{self.name}: Cutter expects NHWC input")
        oshape = self.output_shape_for(self.input.shape)
        if oshape[1] <= 0 or oshape[2] <= 0:
            raise ValueError(f"{self.name}: crop {self.padding} leaves "
                             f"no pixels of {tuple(self.input.shape)}")
        if not self.output:
            self.output.mem = np.zeros(oshape, np.float32)
        self.init_vectors(self.output)

    def _slice(self, x):
        le, to, ri, bo = self.padding
        _, h, w, _ = self.input.shape
        return x[:, to:h - bo, le:w - ri, :]

    def numpy_run(self) -> None:
        self.output.mem = np.ascontiguousarray(self._slice(self.input.mem))

    def xla_run(self) -> None:
        self.output.devmem = self._slice(self.input.devmem)


class GDCutter(GradientDescentBase):
    """Zero-pad err_output back to the input extent."""

    MAPPING = ("cutter",)

    def setup_from_forward(self, fwd) -> "GDCutter":
        super().setup_from_forward(fwd)
        self.padding = fwd.padding
        self.include_bias = False
        return self

    def _pad_spec(self):
        le, to, ri, bo = self.padding
        return ((0, 0), (to, bo), (le, ri), (0, 0))

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        err = self.err_output.mem.reshape(self.output.shape)
        self.err_input.mem = np.pad(err, self._pad_spec())

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        err = self.err_output.devmem.reshape(tuple(self.output.shape))
        self.err_input.devmem = jnp.pad(err, self._pad_spec())


class ChannelMerger(Forward):
    """Concatenate branch outputs on the channel (minor) axis.

    Inputs are linked via ``link_inputs(unit_a, unit_b, ...)``; the unit's
    own ``input`` stays the first branch (chain compatibility)."""

    MAPPING = ("channel_merger",)

    def __init__(self, workflow=None, name=None, **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        self.branches: list = []

    def link_inputs(self, *units) -> "ChannelMerger":
        self.branches = list(units)
        self.link_attrs(units[0], ("input", "output"))
        return self

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.branches:
            raise ValueError(f"{self.name}: link_inputs(...) first")
        shapes = [tuple(u.output.shape) for u in self.branches]
        lead = shapes[0][:-1]
        if any(s[:-1] != lead for s in shapes):
            raise ValueError(f"{self.name}: branch shapes {shapes} differ "
                             "outside the channel axis")
        self.split_sizes = [s[-1] for s in shapes]
        if not self.output:
            self.output.mem = np.zeros((*lead, sum(self.split_sizes)),
                                       np.float32)
        self.init_vectors(self.output)

    def numpy_run(self) -> None:
        self.output.mem = np.concatenate(
            [u.output.mem for u in self.branches], axis=-1)

    def xla_run(self) -> None:
        self.output.devmem = jnp.concatenate(
            [u.output.devmem for u in self.branches], axis=-1)


class GDChannelMerger(GradientDescentBase):
    """Split err_output back into per-branch slices (``err_inputs[i]``)."""

    MAPPING = ("channel_merger",)

    def setup_from_forward(self, fwd) -> "GDChannelMerger":
        super().setup_from_forward(fwd)
        self.split_sizes = fwd.split_sizes
        self.include_bias = False
        self.err_inputs = [Vector() for _ in self.split_sizes]
        return self

    def _split(self, err, xp):
        bounds = np.cumsum(self.split_sizes)[:-1]
        return xp.split(err, bounds, axis=-1)

    def numpy_run(self) -> None:
        err = self.err_output.mem.reshape(self.output.shape)
        for v, part in zip(self.err_inputs, self._split(err, np)):
            v.mem = np.ascontiguousarray(part)
        self.err_input.mem = self.err_inputs[0].mem

    def xla_run(self) -> None:
        err = self.err_output.devmem.reshape(tuple(self.output.shape))
        for v, part in zip(self.err_inputs, self._split(err, jnp)):
            v.devmem = part
        self.err_input.devmem = self.err_inputs[0].devmem


class EltwiseSumMerger(Forward):
    """Elementwise sum of branch outputs (residual-style joins); the
    gradient broadcasts err_output to every branch unchanged."""

    MAPPING = ("sum_merger",)

    def __init__(self, workflow=None, name=None, **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        self.branches: list = []

    def link_inputs(self, *units) -> "EltwiseSumMerger":
        self.branches = list(units)
        self.link_attrs(units[0], ("input", "output"))
        return self

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.branches:
            raise ValueError(f"{self.name}: link_inputs(...) first")
        shapes = {tuple(u.output.shape) for u in self.branches}
        if len(shapes) != 1:
            raise ValueError(f"{self.name}: branch shapes differ: {shapes}")
        if not self.output:
            self.output.mem = np.zeros(next(iter(shapes)), np.float32)
        self.init_vectors(self.output)

    def numpy_run(self) -> None:
        acc = self.branches[0].output.mem.copy()
        for u in self.branches[1:]:
            acc += u.output.mem
        self.output.mem = acc

    def xla_run(self) -> None:
        acc = self.branches[0].output.devmem
        for u in self.branches[1:]:
            acc = acc + u.output.devmem
        self.output.devmem = acc


class GDEltwiseSumMerger(GradientDescentBase):
    MAPPING = ("sum_merger",)

    def setup_from_forward(self, fwd) -> "GDEltwiseSumMerger":
        super().setup_from_forward(fwd)
        self.include_bias = False
        return self

    def numpy_run(self) -> None:
        if self.need_err_input:
            self.err_input.mem = self.err_output.mem.reshape(
                self.output.shape).copy()

    def xla_run(self) -> None:
        if self.need_err_input:
            self.err_input.devmem = self.err_output.devmem.reshape(
                tuple(self.output.shape))
