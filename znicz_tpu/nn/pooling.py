"""Pooling forward units.

Parity target: the reference ``veles/znicz/pooling.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline Pooling]): ``MaxPooling``,
``MaxAbsPooling``, ``AvgPooling``, ``StochasticPooling`` (+abs variant),
storing winner offsets for the backprop scatter.

TPU-first deviations (SURVEY.md §7 hard part (a)): ``input_offset`` holds a
*dense window-slot index* in [0, KH·KW) per output element rather than the
reference's flat global input offsets — a static-shape tensor the XLA
backward turns into compare+add scatter (no gather/scatter engine).
Stochastic pooling draws from the counter-based RNG keyed by
(unit, epoch, minibatch), so numpy and XLA paths pick identical winners
(hard part (c))."""

from __future__ import annotations

import zlib

import numpy as np

from .. import prng
from ..loader.base import TRAIN
from ..memory import Vector
from ..ops import pooling as pool_ops
from .nn_units import Forward


class Pooling(Forward):
    """Shared geometry: kx/ky window, sliding (default = window), padding."""

    MAPPING: tuple[str, ...] = ()

    def __init__(self, workflow=None, name=None, kx=None, ky=None,
                 sliding=None, padding=0, **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        if kx is None:
            raise ValueError("kx is required")
        self.kx = int(kx)
        self.ky = int(ky if ky is not None else kx)
        self.ksize = (self.ky, self.kx)
        self.sliding = (pool_ops._norm2(sliding) if sliding is not None
                        else self.ksize)
        self.padding = pool_ops._norm2(padding)

    def output_shape_for(self, x_shape) -> tuple[int, ...]:
        b, h, w, c = x_shape
        oh = pool_ops.out_size(h, self.ky, self.sliding[0], self.padding[0])
        ow = pool_ops.out_size(w, self.kx, self.sliding[1], self.padding[1])
        return (b, oh, ow, c)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if len(self.input.shape) != 4:
            raise ValueError(
                f"{self.name}: pooling expects NHWC input, got "
                f"{self.input.shape}")
        if not self.output:
            self.output.mem = np.zeros(
                self.output_shape_for(self.input.shape), np.float32)
        self.init_vectors(self.output)


class _OffsetPooling(Pooling):
    """Pooling that records the winner slot for the backward scatter."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.input_offset = Vector()

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.input_offset:
            self.input_offset.mem = np.zeros(self.output.shape, np.int32)
        self.init_vectors(self.input_offset)


class MaxPooling(_OffsetPooling):
    MAPPING = ("max_pooling",)
    _np_fn = staticmethod(pool_ops.np_max_pooling)
    _xla_fn = staticmethod(pool_ops.max_pooling)

    def numpy_run(self) -> None:
        y, idx = self._np_fn(self.input.mem, self.ksize, self.sliding,
                             self.padding)
        self.output.mem, self.input_offset.mem = y, idx

    def xla_run(self) -> None:
        if not hasattr(self, "_fwd_fn"):
            ks, sl, pad = self.ksize, self.sliding, self.padding
            xla_fn = self._xla_fn
            self._fwd_fn = self.jit(lambda x: xla_fn(x, ks, sl, pad))
        y, idx = self._fwd_fn(self.input.devmem)
        self.output.devmem, self.input_offset.devmem = y, idx


class MaxAbsPooling(MaxPooling):
    """Winner is max |value|; output keeps the sign (AlexNet-era trick)."""

    MAPPING = ("maxabs_pooling",)
    _np_fn = staticmethod(pool_ops.np_maxabs_pooling)
    _xla_fn = staticmethod(pool_ops.maxabs_pooling)


class AvgPooling(Pooling):
    MAPPING = ("avg_pooling",)

    def numpy_run(self) -> None:
        self.output.mem = pool_ops.np_avg_pooling(
            self.input.mem, self.ksize, self.sliding, self.padding)

    def xla_run(self) -> None:
        if not hasattr(self, "_fwd_fn"):
            ks, sl, pad = self.ksize, self.sliding, self.padding
            self._fwd_fn = self.jit(
                lambda x: pool_ops.xla_avg_pooling(x, ks, sl, pad))
        self.output.devmem = self._fwd_fn(self.input.devmem)


class StochasticPooling(_OffsetPooling):
    """Zeiler–Fergus stochastic pooling; deterministic weighted mean on
    validation/test minibatches (reference semantics)."""

    MAPPING = ("stochastic_pooling",)
    USE_ABS = False

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.rng = prng.get("pooling")
        # full-name hash: distinct units must get distinct RNG streams
        self.unit_id = zlib.crc32((self.name or "pool").encode())

    def _counters(self) -> tuple[int, int, int]:
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        if loader is None:
            return (self.unit_id, 0, 0)
        return (self.unit_id, loader.epoch_number, loader.minibatch_offset)

    def _is_training(self) -> bool:
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        return loader is None or loader.minibatch_class == TRAIN

    def numpy_run(self) -> None:
        det = not self._is_training()
        u = None if det else pool_ops.stochastic_uniform(
            self.rng.stream_seed, self._counters(),
            self.output.shape, np)
        y, idx = pool_ops.np_stochastic_pooling(
            self.input.mem, self.ksize, self.sliding, self.padding, u,
            use_abs=self.USE_ABS, deterministic=det)
        self.output.mem, self.input_offset.mem = y, idx

    def xla_run(self) -> None:
        import jax.numpy as jnp
        det = not self._is_training()
        u = None if det else pool_ops.stochastic_uniform(
            self.rng.stream_seed, self._counters(),
            self.output.shape, jnp)
        ks, sl, pad, abs_ = self.ksize, self.sliding, self.padding, \
            self.USE_ABS
        key = "det" if det else "rand"
        cache = self.__dict__.setdefault("_fns", {})
        if key not in cache:
            cache[key] = self.jit(
                lambda x, uu: pool_ops.xla_stochastic_pooling(
                    x, ks, sl, pad, uu, use_abs=abs_, deterministic=det))
        y, idx = cache[key](self.input.devmem, u)
        self.output.devmem, self.input_offset.devmem = y, idx


class StochasticAbsPooling(StochasticPooling):
    MAPPING = ("stochastic_abs_pooling",)
    USE_ABS = True
