"""Pooling backprop units.

Parity target: the reference ``veles/znicz/gd_pooling.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline GDPooling]): ``GDMaxPooling``
scatters ``err_output`` to the stored winner offsets; ``GDAvgPooling``
spreads it uniformly over each window.  Pooling has no parameters, so these
units only produce ``err_input`` (apply_gradient is a no-op).

TPU-first: the scatter is an equality-select against the dense window-slot
index plus strided ``.at[].add`` — one VPU pass per window tap, no
gather/scatter engine (SURVEY.md §7 hard part (a))."""

from __future__ import annotations

from ..ops import pooling as pool_ops
from .nn_units import GradientDescentBase


class GDPoolingBase(GradientDescentBase):
    """Shared geometry capture; no weights/bias to update."""

    def setup_from_forward(self, fwd) -> "GDPoolingBase":
        super().setup_from_forward(fwd)
        self.ksize, self.sliding, self.padding = (fwd.ksize, fwd.sliding,
                                                  fwd.padding)
        self.include_bias = False
        return self


class GDMaxPooling(GDPoolingBase):
    """Scatter to the stored winner slot (max / max-abs / stochastic)."""

    MAPPING = ("max_pooling",)

    def setup_from_forward(self, fwd) -> "GDMaxPooling":
        super().setup_from_forward(fwd)
        self.link_attrs(fwd, "input_offset")
        return self

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_input.mem = pool_ops.np_gd_max_pooling(
            self.err_output.mem, self.input_offset.mem, self.input.shape,
            self.ksize, self.sliding, self.padding)

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        if not hasattr(self, "_bwd_fn"):
            ks, sl, pad = self.ksize, self.sliding, self.padding
            x_shape = tuple(self.input.shape)
            self._bwd_fn = self.jit(
                lambda e, off: pool_ops.gd_max_pooling(
                    e, off, x_shape, ks, sl, pad))
        self.err_input.devmem = self._bwd_fn(self.err_output.devmem,
                                             self.input_offset.devmem)


class GDMaxAbsPooling(GDMaxPooling):
    MAPPING = ("maxabs_pooling",)


class GDStochasticPooling(GDMaxPooling):
    MAPPING = ("stochastic_pooling",)


class GDStochasticAbsPooling(GDMaxPooling):
    MAPPING = ("stochastic_abs_pooling",)


class GDAvgPooling(GDPoolingBase):
    MAPPING = ("avg_pooling",)

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_input.mem = pool_ops.np_gd_avg_pooling(
            self.err_output.mem, self.input.shape, self.ksize,
            self.sliding, self.padding)

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        if not hasattr(self, "_bwd_fn"):
            ks, sl, pad = self.ksize, self.sliding, self.padding
            x_shape = tuple(self.input.shape)
            self._bwd_fn = self.jit(
                lambda e: pool_ops.xla_gd_avg_pooling(
                    e, x_shape, ks, sl, pad))
        self.err_input.devmem = self._bwd_fn(self.err_output.devmem)
