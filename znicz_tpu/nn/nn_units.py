"""Shared forward/gradient unit bases.

Parity target: the reference ``veles/znicz/nn_units.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 NN bases row): ``Forward`` with
weights/bias Vectors and gaussian/uniform/constant weight init from the
seeded PRNG; ``GradientDescentBase`` with learning_rate, weights_decay,
l1_vs_l2, gradient_moment (momentum), gradient accumulation, and separate
bias hyperparameters.

Layout note (TPU-first deviation, documented for migrating users): weights
are stored as (n_input, n_output) so the forward matmul is ``x @ W`` with
no transpose — the MXU-friendly layout — where the reference stored
(n_output, n_input) plus a ``weights_transposed`` flag."""

from __future__ import annotations

import numpy as np

from .. import prng
from ..accelerated_units import AcceleratedUnit
from ..memory import Vector
from ..ops import activations


class Forward(AcceleratedUnit):
    """Forward-propagation base unit."""

    #: StandardWorkflow layer-type names this class serves.
    MAPPING: tuple[str, ...] = ()
    ACTIVATION = activations.Activation

    def __init__(self, workflow=None, name=None, weights_filling="uniform",
                 weights_stddev=None, bias_filling="uniform",
                 bias_stddev=None, include_bias=True, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.weights_filling = weights_filling
        self.weights_stddev = weights_stddev
        self.bias_filling = bias_filling
        self.bias_stddev = bias_stddev
        self.include_bias = include_bias
        self.output = Vector()
        self.weights = Vector()
        self.bias = Vector()
        self.prng = prng.get("weights")

    # -- weight init (reference fill semantics) ---------------------------
    def _fill(self, shape: tuple[int, ...], filling: str,
              stddev: float | None) -> np.ndarray:
        fan_in = int(np.prod(shape[:-1])) if len(shape) > 1 else shape[0]
        stddev = stddev if stddev is not None else 1.0 / max(
            np.sqrt(fan_in), 1.0)
        if filling == "uniform":
            return self.prng.uniform(-stddev, stddev, shape)
        if filling == "gaussian":
            return self.prng.normal(0.0, stddev, shape)
        if filling == "constant":
            return np.full(shape, stddev, np.float32)
        raise ValueError(f"unknown filling {filling!r}")

    def create_weights(self, w_shape: tuple[int, ...],
                       b_shape: tuple[int, ...]) -> None:
        if not self.weights:
            self.weights.mem = self._fill(w_shape, self.weights_filling,
                                          self.weights_stddev)
        if self.include_bias and not self.bias:
            self.bias.mem = self._fill(b_shape, self.bias_filling,
                                       self.bias_stddev
                                       if self.bias_stddev is not None
                                       else 0.0)
            if self.bias_filling == "uniform" and self.bias_stddev is None:
                self.bias.mem = np.zeros(b_shape, np.float32)


class GradientDescentBase(AcceleratedUnit):
    """Backprop base unit (the reference's hand-written gradient units).

    Wired to its paired Forward via ``setup_from_forward``: shares the
    *same* weights/bias Vectors (updates are visible to the forward unit),
    links input/output, and produces ``err_input`` for the previous GD unit
    from ``err_output`` supplied by the next one (or the evaluator)."""

    MAPPING: tuple[str, ...] = ()
    ACTIVATION = activations.Activation

    def __init__(self, workflow=None, name=None, learning_rate=0.01,
                 learning_rate_bias=None, weights_decay=0.0,
                 weights_decay_bias=0.0, l1_vs_l2=0.0, l1_vs_l2_bias=0.0,
                 gradient_moment=0.0, gradient_moment_bias=None,
                 apply_gradient=True, need_err_input=True,
                 accumulate_gradient=False, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.learning_rate = learning_rate
        self.learning_rate_bias = (learning_rate_bias
                                   if learning_rate_bias is not None
                                   else learning_rate)
        self.weights_decay = weights_decay
        self.weights_decay_bias = weights_decay_bias
        self.l1_vs_l2 = l1_vs_l2
        self.l1_vs_l2_bias = l1_vs_l2_bias
        self.gradient_moment = gradient_moment
        self.gradient_moment_bias = (gradient_moment_bias
                                     if gradient_moment_bias is not None
                                     else gradient_moment)
        self.apply_gradient = apply_gradient
        self.need_err_input = need_err_input
        self.accumulate_gradient = accumulate_gradient
        self.err_input = Vector()
        self.gradient_weights = Vector()
        self.gradient_bias = Vector()
        self.velocity_weights = Vector()
        self.velocity_bias = Vector()
        self.forward_unit: Forward | None = None

    def setup_from_forward(self, fwd: Forward) -> "GradientDescentBase":
        self.forward_unit = fwd
        self.link_attrs(fwd, "weights", "bias", "input", "output")
        self.include_bias = fwd.include_bias
        return self

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if self.weights and not self.velocity_weights:
            self.velocity_weights.mem = np.zeros(self.weights.shape,
                                                 np.float32)
        if self.include_bias and self.bias and not self.velocity_bias:
            self.velocity_bias.mem = np.zeros(self.bias.shape, np.float32)
        self.init_vectors(self.err_input, self.gradient_weights,
                          self.gradient_bias, self.velocity_weights,
                          self.velocity_bias)

    # -- distributed contract (SURVEY.md §2.4) ----------------------------
    def generate_data_for_master(self):
        """The pytree this unit contributes to gradient aggregation."""
        return {"weights": self.gradient_weights.mem,
                "bias": self.gradient_bias.mem if self.include_bias
                else None}

    def apply_data_from_slave(self, data, slave=None) -> None:
        """Host-side fold (golden path only; the XLA path psums on-device)."""
        if data is None:
            return
        self.gradient_weights.map_write()
        self.gradient_weights.mem += data["weights"]
        if self.include_bias and data.get("bias") is not None:
            self.gradient_bias.map_write()
            self.gradient_bias.mem += data["bias"]
