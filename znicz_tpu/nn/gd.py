"""Backprop units for fully-connected layers.

Parity target: the reference ``veles/znicz/gd.py`` (mount empty — surveyed
contract, SURVEY.md §2.2 [baseline GradientDescent*]): hand-written
gradients — err_input via matmul with Wᵀ, weight/bias gradients via xᵀ·err,
SGD + momentum + L1/L2 update (the reference's matmul + ``weights_update``
kernels → Pallas matmul + fused update kernel here).

Math (per activation variant): ``err_y = act.bwd(err_output, y)``;
``∇W = xᵀ·err_y``; ``∇b = Σ err_y``; ``err_input = err_y·Wᵀ``.  The
evaluator already scales err_output by 1/batch and zeroes padded rows, so
no batch normalization happens here (matches the reference's division of
labor).  Tests cross-check this chain against ``jax.grad`` (SURVEY.md §7).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..ops import activations, matmul, update
from .nn_units import GradientDescentBase


class GradientDescent(GradientDescentBase):
    """Gradient unit for All2All (linear activation)."""

    MAPPING = ("all2all",)
    ACTIVATION = activations.Activation

    def _hypers(self):
        return (self.learning_rate, self.weights_decay, self.l1_vs_l2,
                self.gradient_moment)

    def _hypers_bias(self):
        return (self.learning_rate_bias, self.weights_decay_bias,
                self.l1_vs_l2_bias, self.gradient_moment_bias)

    def numpy_run(self) -> None:
        act = self.ACTIVATION
        y = self.output.mem
        y2 = y.reshape(len(y), -1)
        err_y = act.bwd(self.err_output.mem.reshape(y2.shape), y2,
                        self.input.mem.reshape(y2.shape[0], -1)
                        if act.needs_input else None, np)
        x = self.input.mem.reshape(len(self.input.mem), -1)
        gw = matmul.np_matmul(x.T, err_y)
        gb = err_y.sum(axis=0) if self.include_bias else None
        if self.accumulate_gradient and self.gradient_weights:
            gw = gw + self.gradient_weights.mem
            if gb is not None:
                gb = gb + self.gradient_bias.mem
        self.gradient_weights.mem = gw
        if gb is not None:
            self.gradient_bias.mem = gb
        if self.need_err_input:
            self.err_input.mem = matmul.np_matmul(
                err_y, self.weights.mem.T).reshape(self.input.shape)
        if self.apply_gradient:
            w, vw = update.np_sgd_update(self.weights.mem, gw,
                                         self.velocity_weights.mem,
                                         *self._hypers())
            self.weights.mem = w
            self.velocity_weights.mem = vw
            if self.include_bias:
                b, vb = update.np_sgd_update(self.bias.mem, gb,
                                             self.velocity_bias.mem,
                                             *self._hypers_bias())
                self.bias.mem = b
                self.velocity_bias.mem = vb

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        act = self.ACTIVATION
        need_err = self.need_err_input
        include_bias = self.include_bias

        def bwd(x, w, err_out, y):
            b = x.shape[0]
            x2 = x.reshape(b, -1)
            y2 = y.reshape(b, -1)
            err_y = act.bwd(err_out.reshape(y2.shape), y2,
                            x2 if act.needs_input else None, jnp)
            gw = matmul.matmul(x2.T, err_y)
            gb = jnp.sum(err_y, axis=0) if include_bias else None
            err_in = (matmul.matmul(err_y, w.T).reshape(x.shape)
                      if need_err else None)
            return gw, gb, err_in

        self._bwd_fn = bwd
        # one dispatch point for the fused update kernel (ops.update)
        self._apply_fn = update.sgd_update_h

    def xla_run(self) -> None:
        bwd = self.jit(self._bwd_fn)
        gw, gb, err_in = bwd(self.input.devmem, self.weights.devmem,
                             self.err_output.devmem, self.output.devmem)
        if self.accumulate_gradient and self.gradient_weights:
            gw = gw + self.gradient_weights.devmem
            if gb is not None:
                gb = gb + self.gradient_bias.devmem
        self.gradient_weights.devmem = gw
        if gb is not None:
            self.gradient_bias.devmem = gb
        if self.need_err_input:
            self.err_input.devmem = err_in
        if self.apply_gradient:
            apply_fn = self.jit(self._apply_fn)
            hw = jnp.asarray(self._hypers(), jnp.float32)
            w, vw = apply_fn(self.weights.devmem, gw,
                             self.velocity_weights.devmem, hw)
            self.weights.devmem = w
            self.velocity_weights.devmem = vw
            if self.include_bias:
                hb = jnp.asarray(self._hypers_bias(), jnp.float32)
                b, vb = apply_fn(self.bias.devmem, gb,
                                 self.velocity_bias.devmem, hb)
                self.bias.devmem = b
                self.velocity_bias.devmem = vb


class GDTanh(GradientDescent):
    MAPPING = ("all2all_tanh",)
    ACTIVATION = activations.Tanh


class GDRELU(GradientDescent):
    MAPPING = ("all2all_relu",)
    ACTIVATION = activations.Relu


class GDStrictRELU(GradientDescent):
    MAPPING = ("all2all_str",)
    ACTIVATION = activations.StrictRelu


class GDSigmoid(GradientDescent):
    MAPPING = ("all2all_sigmoid",)
    ACTIVATION = activations.Sigmoid


class GDSoftmax(GradientDescent):
    """Softmax layer backprop: EvaluatorSoftmax supplies the error already
    w.r.t. the *logits* (y − onehot), so the activation pass-through is the
    identity (matches the reference's GDSoftmax)."""

    MAPPING = ("softmax",)
    ACTIVATION = activations.Activation


#: Reference short alias
GD = GradientDescent
