"""Dropout units.

Parity target: the reference ``veles/znicz/dropout.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline Dropout]): ``DropoutForward``
generates a Bernoulli keep-mask at train time (identity on validation/test),
``DropoutBackward`` scales the error by the same mask.

TPU-first (SURVEY.md §7 hard part (c)): the mask comes from the
counter-based hash RNG keyed by (unit, epoch, minibatch), so numpy and XLA
paths produce bit-identical masks; inverted scaling (kept units ×
1/(1−ratio)) keeps eval a plain identity."""

from __future__ import annotations

import zlib

import numpy as np

import jax.numpy as jnp

from .. import prng
from ..loader.base import TRAIN
from ..memory import Vector
from ..ops import dropout as drop_ops
from .nn_units import Forward, GradientDescentBase


class DropoutForward(Forward):
    MAPPING = ("dropout",)

    def __init__(self, workflow=None, name=None, dropout_ratio=0.5,
                 **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        self.dropout_ratio = float(dropout_ratio)
        self.mask = Vector()
        self.rng = prng.get("dropout")
        # full-name hash: distinct units must get distinct RNG streams
        self.unit_id = zlib.crc32((self.name or "dropout").encode())
        self.training = True   # loader-less (unit-test) default

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.output:
            self.output.mem = np.zeros(self.input.shape, np.float32)
        if not self.mask:
            self.mask.mem = np.ones(self.input.shape, np.float32)
        self.init_vectors(self.output, self.mask)

    def _counters(self) -> tuple[int, int, int]:
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        if loader is None:
            return (self.unit_id, 0, 0)
        return (self.unit_id, loader.epoch_number, loader.minibatch_offset)

    def _is_training(self) -> bool:
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        return self.training if loader is None \
            else loader.minibatch_class == TRAIN

    def numpy_run(self) -> None:
        if not self._is_training():
            self.mask.mem = np.ones(self.input.shape, np.float32)
            self.output.mem = self.input.mem.copy()
            return
        mask = drop_ops.make_mask(self.rng.stream_seed, self._counters(),
                                  self.input.shape, self.dropout_ratio, np)
        self.mask.mem = mask
        self.output.mem = drop_ops.np_dropout(self.input.mem, mask)

    def xla_run(self) -> None:
        if not self._is_training():
            self.mask.devmem = jnp.ones(self.input.shape, jnp.float32)
            self.output.devmem = self.input.devmem
            return
        if not hasattr(self, "_fwd_fn"):
            from ..ops import tuning
            seed, ratio = self.rng.stream_seed, self.dropout_ratio
            shape = tuple(self.input.shape)
            use_pallas = tuning.use_pallas()

            def fwd(x, counters):
                mask = drop_ops.make_mask(seed, counters, shape, ratio,
                                          jnp)
                if use_pallas:
                    # fused mask-gen+apply kernel; the hash inside is
                    # bit-identical to make_mask, so mask stays the
                    # published contract for DropoutBackward
                    y = drop_ops.dropout_apply(x, seed, counters, ratio)
                else:
                    y = drop_ops.xla_dropout(x, mask)
                return y, mask

            self._fwd_fn = fwd
        y, mask = self.jit(self._fwd_fn)(
            self.input.devmem,
            jnp.asarray(self._counters(), jnp.uint32))
        self.output.devmem, self.mask.devmem = y, mask


class DropoutBackward(GradientDescentBase):
    """err_input = err_output ⊙ mask; no parameters."""

    MAPPING = ("dropout",)

    def setup_from_forward(self, fwd) -> "DropoutBackward":
        super().setup_from_forward(fwd)
        self.link_attrs(fwd, "mask")
        self.include_bias = False
        return self

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_input.mem = drop_ops.np_gd_dropout(self.err_output.mem,
                                                    self.mask.mem)

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        if not hasattr(self, "_bwd_fn"):
            self._bwd_fn = self.jit(drop_ops.xla_gd_dropout)
        self.err_input.devmem = self._bwd_fn(self.err_output.devmem,
                                             self.mask.devmem)
