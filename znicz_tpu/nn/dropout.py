"""Dropout units.

Parity target: the reference ``veles/znicz/dropout.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline Dropout]): ``DropoutForward``
generates a Bernoulli keep-mask at train time (identity on validation/test),
``DropoutBackward`` scales the error by the same mask.

TPU-first (SURVEY.md §7 hard part (c)): the mask comes from the
counter-based hash RNG keyed by (unit, epoch, minibatch), so numpy and XLA
paths produce bit-identical masks; inverted scaling (kept units ×
1/(1−ratio)) keeps eval a plain identity."""

from __future__ import annotations

import zlib

import numpy as np

import jax.numpy as jnp

from .. import prng
from ..loader.base import TRAIN
from ..memory import Vector
from ..ops import dropout as drop_ops
from .nn_units import Forward, GradientDescentBase


class DropoutForward(Forward):
    MAPPING = ("dropout",)

    def __init__(self, workflow=None, name=None, dropout_ratio=0.5,
                 **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        self.dropout_ratio = float(dropout_ratio)
        self.mask = Vector()
        self.rng = prng.get("dropout")
        # full-name hash: distinct units must get distinct RNG streams
        self.unit_id = zlib.crc32((self.name or "dropout").encode())
        self.training = True   # loader-less (unit-test) default

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.output:
            self.output.mem = np.zeros(self.input.shape, np.float32)
        from ..ops import tuning
        if tuning.use_pallas() and device is not None and device.is_xla:
            # Pallas contract: the fused kernel never materializes the
            # mask — leave the Vector EMPTY (falsy) rather than uploading
            # an input-sized all-ones buffer a reader could mistake for
            # the real thing; DropoutBackward regenerates the stream from
            # (seed, counters) instead
            self.init_vectors(self.output)
            return
        if not self.mask:
            self.mask.mem = np.ones(self.input.shape, np.float32)
        self.init_vectors(self.output, self.mask)

    def _counters(self) -> tuple[int, int, int]:
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        if loader is None:
            return (self.unit_id, 0, 0)
        return (self.unit_id, loader.epoch_number, loader.minibatch_offset)

    def _is_training(self) -> bool:
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        return self.training if loader is None \
            else loader.minibatch_class == TRAIN

    def numpy_run(self) -> None:
        if not self._is_training():
            self.mask.mem = np.ones(self.input.shape, np.float32)
            self.output.mem = self.input.mem.copy()
            return
        mask = drop_ops.make_mask(self.rng.stream_seed, self._counters(),
                                  self.input.shape, self.dropout_ratio, np)
        self.mask.mem = mask
        self.output.mem = drop_ops.np_dropout(self.input.mem, mask)

    def xla_run(self) -> None:
        if not self._is_training():
            from ..ops import tuning
            if not tuning.use_pallas():    # pallas mode: mask stays empty
                self.mask.devmem = jnp.ones(self.input.shape, jnp.float32)
            self.output.devmem = self.input.devmem
            return
        if not hasattr(self, "_fwd_fn"):
            from ..ops import tuning
            seed, ratio = self.rng.stream_seed, self.dropout_ratio
            shape = tuple(self.input.shape)
            self._use_pallas = tuning.use_pallas()

            if self._use_pallas:
                # fused mask-gen+apply kernel, ONE HBM pass: the mask is
                # NOT materialized here — DropoutBackward regenerates the
                # identical stream from (seed, counters) (ADVICE r1: the
                # old path paid a second full mask pass)
                def fwd(x, counters):
                    return drop_ops.dropout_apply(x, seed, counters,
                                                  ratio)
            else:
                def fwd(x, counters):
                    mask = drop_ops.make_mask(seed, counters, shape,
                                              ratio, jnp)
                    return drop_ops.xla_dropout(x, mask), mask

            self._fwd_fn = fwd
        ctrs = tuple(int(c) for c in self._counters())
        out = self.jit(self._fwd_fn)(self.input.devmem,
                                     jnp.asarray(ctrs, jnp.uint32))
        if self._use_pallas:
            self.output.devmem = out
            self._last_counters = ctrs     # mask contract for backward
        else:
            self.output.devmem, self.mask.devmem = out


class DropoutBackward(GradientDescentBase):
    """err_input = err_output ⊙ mask; no parameters."""

    MAPPING = ("dropout",)

    def setup_from_forward(self, fwd) -> "DropoutBackward":
        super().setup_from_forward(fwd)
        self.link_attrs(fwd, "mask")
        self._fwd_unit = fwd
        self.include_bias = False
        return self

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_input.mem = drop_ops.np_gd_dropout(self.err_output.mem,
                                                    self.mask.mem)

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        ctrs = getattr(self._fwd_unit, "_last_counters", None) \
            if getattr(self._fwd_unit, "_use_pallas", False) else None
        if ctrs is not None:
            # Pallas contract: the forward published no mask; regenerate
            # the identical (seed, counters) stream fused with the apply
            if not hasattr(self, "_bwd_pallas_fn"):
                seed = self._fwd_unit.rng.stream_seed
                ratio = self._fwd_unit.dropout_ratio
                self._bwd_pallas_fn = self.jit(
                    lambda e, c: drop_ops.dropout_apply(e, seed, c,
                                                        ratio))
            self.err_input.devmem = self._bwd_pallas_fn(
                self.err_output.devmem, jnp.asarray(ctrs, jnp.uint32))
            return
        if not hasattr(self, "_bwd_fn"):
            self._bwd_fn = self.jit(drop_ops.xla_gd_dropout)
        self.err_input.devmem = self._bwd_fn(self.err_output.devmem,
                                             self.mask.devmem)
