"""Evaluators: loss + error statistics at the end of the forward chain.

Parity target: the reference ``veles/znicz/evaluator.py`` (mount empty —
surveyed contract, SURVEY.md §2.2): ``EvaluatorSoftmax`` (cross-entropy,
``n_err`` count, confusion matrix, ``max_err_output_sum``) and
``EvaluatorMSE``.  Produces ``err_output`` consumed by the last GD unit.

Division of labor (matches reference): the evaluator scales the error by
1/batch_size; GD units apply it raw.  TPU-first addition: padded rows of a
short final minibatch are zeroed here so downstream gradient math needs no
masking."""

from __future__ import annotations

import numpy as np


from ..accelerated_units import AcceleratedUnit
from ..memory import Vector
from ..ops import softmax as softmax_ops


class EvaluatorBase(AcceleratedUnit):
    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.err_output = Vector()
        self.mean_loss = 0.0

    @property
    def batch_size(self) -> int:
        return self.loader.minibatch_size

    def link_loader(self, loader) -> None:
        self.loader = loader


class EvaluatorSoftmax(EvaluatorBase):
    """Cross-entropy evaluator over All2AllSoftmax output.

    Inputs (linked): ``output`` (softmax probs), ``max_idx``, ``labels``.
    Outputs: ``err_output`` = (y − onehot)/batch (padded rows zeroed),
    ``n_err`` (this minibatch's miss count), ``confusion_matrix``,
    ``max_err_output_sum``."""

    def __init__(self, workflow=None, name=None, compute_confusion=True,
                 **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.n_err = 0
        self.compute_confusion = compute_confusion
        self.confusion_matrix = Vector()
        self.max_err_output_sum = 0.0

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        n_classes = self.output.shape[1]
        if self.compute_confusion and not self.confusion_matrix:
            self.confusion_matrix.mem = np.zeros((n_classes, n_classes),
                                                 np.int64)
        self.init_vectors(self.err_output, self.confusion_matrix)
        self._confusion_epoch = -1

    def numpy_run(self) -> None:
        bs = self.batch_size
        y = self.output.mem
        labels = self.labels.mem.astype(np.int64)
        loss, err = softmax_ops.np_softmax_ce(y[:bs], labels[:bs])
        full = np.zeros(y.shape, np.float32)
        full[:bs] = err / bs
        self.err_output.mem = full
        pred = self.max_idx.mem[:bs]
        self.n_err = int(np.sum(pred != labels[:bs]))
        self.mean_loss = float(loss.mean())
        self.max_err_output_sum = float(np.abs(full).sum(axis=1).max())
        if self.compute_confusion:
            epoch = getattr(self.loader, "epoch_number", 0)
            self.confusion_matrix.map_write()
            if epoch != self._confusion_epoch:   # fresh matrix per epoch
                self.confusion_matrix.mem[...] = 0
                self._confusion_epoch = epoch
            np.add.at(self.confusion_matrix.mem, (labels[:bs], pred), 1)

    def xla_run(self) -> None:
        # Metrics are host-side scalars consumed by Decision each tick, so
        # compute on host from mapped outputs (tiny: batch × classes), but
        # build err_output with the same math as numpy_run.
        self.numpy_run()


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error evaluator (reference EvaluatorMSE contract):
    err_output = (y − target)/batch; metrics: per-minibatch mse and rmse."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.mse = 0.0
        self.n_err = 0   # uniform Decision interface: mse-thresholded count

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        self.init_vectors(self.err_output)

    def numpy_run(self) -> None:
        bs = self.batch_size
        y = self.output.mem.reshape(len(self.output.mem), -1)
        t = self.target.mem.reshape(y.shape)
        err = np.zeros(y.shape, np.float32)
        err[:bs] = (y[:bs] - t[:bs]) / bs
        self.err_output.mem = err.reshape(self.output.shape)
        sq = ((y[:bs] - t[:bs]) ** 2).mean(axis=1)
        self.mse = float(sq.mean())
        self.mean_loss = self.mse
        self.n_err = int(bs)   # decision tracks loss for MSE flows

    def xla_run(self) -> None:
        self.numpy_run()
