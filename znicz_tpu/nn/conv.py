"""2-D convolution forward units.

Parity target: the reference ``veles/znicz/conv.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline Conv]): ``Conv`` with
``n_kernels``/``kx``/``ky``/``sliding``/``padding`` and fused-activation
variants ``ConvTanh``/``ConvRELU``/``ConvStrictRELU``.  The reference's
block-tiled unpack-in-kernel ``conv.cl``/``conv.cu`` becomes the
``ops.conv`` tiers (XLA ``conv_general_dilated`` onto the MXU; Pallas
implicit-GEMM option).

TPU-first deviations (documented for migrating users):

* Layout is NHWC with HWIO weights — channels ride the 128-lane minor dim
  (the reference flattened samples row-major and unpacked inside the
  kernel).
* ``padding`` is symmetric ``int`` or ``(pad_h, pad_w)`` — the reference's
  4-tuple (left, top, right, bottom) collapses to the symmetric case used
  by every shipped sample.
* Bias + activation fuse into the conv's HBM pass under jit (the GPU
  kernel did this by hand)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..ops import activations, conv as conv_ops
from .nn_units import Forward


class Conv(Forward):
    """y = act(conv2d(x, W) + b); x is (B, H, W, C), W is (ky, kx, C, OC)."""

    MAPPING = ("conv",)
    ACTIVATION = activations.Activation

    def __init__(self, workflow=None, name=None, n_kernels=None, kx=None,
                 ky=None, sliding=1, padding=0, **kwargs):
        kwargs.setdefault("weights_filling", "gaussian")
        super().__init__(workflow, name, **kwargs)
        if n_kernels is None or kx is None:
            raise ValueError("n_kernels and kx are required")
        self.n_kernels = int(n_kernels)
        self.kx = int(kx)
        self.ky = int(ky if ky is not None else kx)
        self.sliding = conv_ops._norm2(sliding)
        self.padding = conv_ops._norm2(padding)

    def output_shape_for(self, x_shape) -> tuple[int, ...]:
        b, h, w, _ = x_shape
        oh = conv_ops.out_size(h, self.ky, self.sliding[0], self.padding[0])
        ow = conv_ops.out_size(w, self.kx, self.sliding[1], self.padding[1])
        return (b, oh, ow, self.n_kernels)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if len(self.input.shape) != 4:
            raise ValueError(
                f"{self.name}: Conv expects NHWC input, got shape "
                f"{self.input.shape}")
        c = self.input.shape[3]
        self.create_weights((self.ky, self.kx, c, self.n_kernels),
                            (self.n_kernels,))
        if not self.output:
            self.output.mem = np.zeros(
                self.output_shape_for(self.input.shape), np.float32)
        self.init_vectors(self.weights, self.bias, self.output)
        act, sliding, padding = self.ACTIVATION, self.sliding, self.padding

        def fwd(x, w, b):
            y = conv_ops.conv2d(x, w, sliding, padding)
            if b is not None:
                y = y + b
            return act.fwd(y, jnp)

        self._fwd_fn = fwd

    def numpy_run(self) -> None:
        y = conv_ops.np_conv2d(self.input.mem, self.weights.mem,
                               self.sliding, self.padding)
        if self.include_bias:
            y = y + self.bias.mem
        self.output.mem = self.ACTIVATION.fwd(y, np)

    def xla_run(self) -> None:
        fn = self.jit(self._fwd_fn)
        self.output.devmem = fn(
            self.input.devmem, self.weights.devmem,
            self.bias.devmem if self.include_bias else None)


class ConvTanh(Conv):
    MAPPING = ("conv_tanh",)
    ACTIVATION = activations.Tanh


class ConvRELU(Conv):
    """Smooth relu log(1+eˣ) — the reference's RELU (SURVEY.md §2.2)."""

    MAPPING = ("conv_relu",)
    ACTIVATION = activations.Relu


class ConvStrictRELU(Conv):
    MAPPING = ("conv_str",)
    ACTIVATION = activations.StrictRelu


class ConvSigmoid(Conv):
    MAPPING = ("conv_sigmoid",)
    ACTIVATION = activations.Sigmoid
