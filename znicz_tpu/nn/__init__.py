"""The NN unit zoo (reference: veles/znicz plugin — SURVEY.md §2.2).

Forward/gradient unit pairs, evaluators, decision logic.  Each forward unit
has a ``numpy_run`` golden path and an ``xla_run`` accelerated path (XLA +
Pallas kernels from ``znicz_tpu.ops``); gradient units carry the
hand-written backward math the reference shipped (cross-checked against
``jax.grad`` in tests)."""

from .all2all import (All2All, All2AllRELU, All2AllSigmoid, All2AllSoftmax,
                      All2AllStrictRELU, All2AllTanh)
from .decision import DecisionBase, DecisionGD, DecisionMSE
from .evaluator import EvaluatorMSE, EvaluatorSoftmax
from .gd import (GD, GDRELU, GDSigmoid, GDSoftmax, GDStrictRELU, GDTanh,
                 GradientDescent)
from .nn_units import Forward, GradientDescentBase

__all__ = [
    "All2All", "All2AllRELU", "All2AllSigmoid", "All2AllSoftmax",
    "All2AllStrictRELU", "All2AllTanh", "DecisionBase", "DecisionGD",
    "DecisionMSE", "EvaluatorMSE", "EvaluatorSoftmax", "Forward", "GD",
    "GDRELU", "GDSigmoid", "GDSoftmax", "GDStrictRELU", "GDTanh",
    "GradientDescent", "GradientDescentBase",
]
