"""The NN unit zoo (reference: veles/znicz plugin — SURVEY.md §2.2).

Forward/gradient unit pairs, evaluators, decision logic.  Each forward unit
has a ``numpy_run`` golden path and an ``xla_run`` accelerated path (XLA +
Pallas kernels from ``znicz_tpu.ops``); gradient units carry the
hand-written backward math the reference shipped (cross-checked against
``jax.grad`` in tests)."""

from .all2all import (All2All, All2AllRELU, All2AllSigmoid, All2AllSoftmax,
                      All2AllStrictRELU, All2AllTanh)
from .conv import Conv, ConvRELU, ConvSigmoid, ConvStrictRELU, ConvTanh
from .decision import DecisionBase, DecisionGD, DecisionMSE
from .dropout import DropoutBackward, DropoutForward
from .evaluator import EvaluatorMSE, EvaluatorSoftmax
from .gd import (GD, GDRELU, GDSigmoid, GDSoftmax, GDStrictRELU, GDTanh,
                 GradientDescent)
from .gd_conv import (GDRELUConv, GDSigmoidConv, GDStrictRELUConv,
                      GDTanhConv, GradientDescentConv)
from .gd_pooling import (GDAvgPooling, GDMaxAbsPooling, GDMaxPooling,
                         GDStochasticAbsPooling, GDStochasticPooling)
from .cutter import (ChannelMerger, Cutter, EltwiseSumMerger,
                     GDChannelMerger, GDCutter, GDEltwiseSumMerger)
from .deconv import Deconv, DeconvSigmoid, DeconvTanh
from .gd_deconv import GDDeconv, GDDeconvSigmoid, GDDeconvTanh
from .depooling import Depooling, GDDepooling
from .kohonen import (KohonenDecision, KohonenForward, KohonenTrainer)
from .lr_adjust import LearningRateAdjust, make_policy
from .rbm_units import RBM, Binarization, RBMTrainer
from .nn_units import Forward, GradientDescentBase
from .normalization import LRNormalizerBackward, LRNormalizerForward
from .pooling import (AvgPooling, MaxAbsPooling, MaxPooling, Pooling,
                      StochasticAbsPooling, StochasticPooling)

__all__ = [
    "ChannelMerger", "Cutter", "EltwiseSumMerger", "GDChannelMerger",
    "GDCutter", "GDEltwiseSumMerger", "LearningRateAdjust", "make_policy", "RBM", "Binarization",
    "RBMTrainer",
    "Deconv", "DeconvSigmoid", "DeconvTanh", "Depooling", "GDDeconv",
    "GDDeconvSigmoid", "GDDeconvTanh", "GDDepooling", "KohonenDecision",
    "KohonenForward", "KohonenTrainer",
    "All2All", "All2AllRELU", "All2AllSigmoid", "All2AllSoftmax",
    "All2AllStrictRELU", "All2AllTanh", "AvgPooling", "Conv", "ConvRELU",
    "ConvSigmoid", "ConvStrictRELU", "ConvTanh", "DecisionBase",
    "DecisionGD", "DecisionMSE", "DropoutBackward", "DropoutForward",
    "EvaluatorMSE", "EvaluatorSoftmax", "Forward", "GD", "GDAvgPooling",
    "GDMaxAbsPooling", "GDMaxPooling", "GDRELU", "GDRELUConv",
    "GDSigmoid", "GDSigmoidConv", "GDSoftmax", "GDStochasticAbsPooling",
    "GDStochasticPooling", "GDStrictRELU", "GDStrictRELUConv", "GDTanh",
    "GDTanhConv", "GradientDescent", "GradientDescentBase",
    "GradientDescentConv", "LRNormalizerBackward", "LRNormalizerForward",
    "MaxAbsPooling", "MaxPooling", "Pooling", "StochasticAbsPooling",
    "StochasticPooling",
]
