"""Backprop unit for the transposed convolution.

Parity target: the reference ``veles/znicz/gd_deconv.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline GDDeconv]).

The adjoint relationship makes the gradients *conv* ops (see
``ops.deconv``): err_input is a plain conv of err_output with the shared
weights; the weight grad is the conv weight-grad with the input/error
roles swapped.  Tests cross-check the whole chain against ``jax.grad``."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..ops import activations, deconv as deconv_ops, update
from .nn_units import GradientDescentBase


class GDDeconv(GradientDescentBase):
    """Gradient unit for Deconv."""

    MAPPING = ("deconv",)
    ACTIVATION = activations.Activation

    def _hypers(self):
        return (self.learning_rate, self.weights_decay, self.l1_vs_l2,
                self.gradient_moment)

    def _hypers_bias(self):
        return (self.learning_rate_bias, self.weights_decay_bias,
                self.l1_vs_l2_bias, self.gradient_moment_bias)

    def setup_from_forward(self, fwd) -> "GDDeconv":
        super().setup_from_forward(fwd)
        self.sliding, self.padding = fwd.sliding, fwd.padding
        return self

    def numpy_run(self) -> None:
        act = self.ACTIVATION
        y = self.output.mem
        err_y = act.bwd(self.err_output.mem.reshape(y.shape), y,
                        self.input.mem if act.needs_input else None, np)
        x = self.input.mem
        gw = deconv_ops.np_deconv2d_grad_weights(
            err_y, x, self.weights.shape, self.sliding, self.padding)
        gb = err_y.sum(axis=(0, 1, 2)) if self.include_bias else None
        if self.accumulate_gradient and self.gradient_weights:
            gw = gw + self.gradient_weights.mem
            if gb is not None:
                gb = gb + self.gradient_bias.mem
        self.gradient_weights.mem = gw
        if gb is not None:
            self.gradient_bias.mem = gb
        if self.need_err_input:
            self.err_input.mem = deconv_ops.np_deconv2d_grad_input(
                err_y, self.weights.mem, self.sliding, self.padding)
        if self.apply_gradient:
            w, vw = update.np_sgd_update(self.weights.mem, gw,
                                         self.velocity_weights.mem,
                                         *self._hypers())
            self.weights.mem, self.velocity_weights.mem = w, vw
            if self.include_bias:
                b, vb = update.np_sgd_update(self.bias.mem, gb,
                                             self.velocity_bias.mem,
                                             *self._hypers_bias())
                self.bias.mem, self.velocity_bias.mem = b, vb

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        act = self.ACTIVATION
        need_err = self.need_err_input
        include_bias = self.include_bias
        sliding, padding = self.sliding, self.padding
        w_shape = tuple(self.weights.shape)

        def bwd(x, w, err_out, y):
            err_y = act.bwd(err_out.reshape(y.shape), y,
                            x if act.needs_input else None, jnp)
            gw = deconv_ops.deconv2d_grad_weights(err_y, x, w_shape,
                                                  sliding, padding)
            gb = jnp.sum(err_y, axis=(0, 1, 2)) if include_bias else None
            err_in = (deconv_ops.deconv2d_grad_input(
                err_y, w, sliding, padding) if need_err else None)
            return gw, gb, err_in

        self._bwd_fn = bwd
        self._apply_fn = update.sgd_update_h

    def xla_run(self) -> None:
        bwd = self.jit(self._bwd_fn)
        gw, gb, err_in = bwd(self.input.devmem, self.weights.devmem,
                             self.err_output.devmem, self.output.devmem)
        if self.accumulate_gradient and self.gradient_weights:
            gw = gw + self.gradient_weights.devmem
            if gb is not None:
                gb = gb + self.gradient_bias.devmem
        self.gradient_weights.devmem = gw
        if gb is not None:
            self.gradient_bias.devmem = gb
        if self.need_err_input:
            self.err_input.devmem = err_in
        if self.apply_gradient:
            apply_fn = self.jit(self._apply_fn)
            hw = jnp.asarray(self._hypers(), jnp.float32)
            w, vw = apply_fn(self.weights.devmem, gw,
                             self.velocity_weights.devmem, hw)
            self.weights.devmem, self.velocity_weights.devmem = w, vw
            if self.include_bias:
                hb = jnp.asarray(self._hypers_bias(), jnp.float32)
                b, vb = apply_fn(self.bias.devmem, gb,
                                 self.velocity_bias.devmem, hb)
                self.bias.devmem, self.velocity_bias.devmem = b, vb


class GDDeconvTanh(GDDeconv):
    MAPPING = ("deconv_tanh",)
    ACTIVATION = activations.Tanh


class GDDeconvSigmoid(GDDeconv):
    MAPPING = ("deconv_sigmoid",)
    ACTIVATION = activations.Sigmoid
