"""Transposed-convolution forward unit (autoencoder decoder).

Parity target: the reference ``veles/znicz/deconv.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline Deconv]): ``Deconv`` with shape
inference from (and optional weight tying to) a paired encoder ``Conv``,
plus the ``compute_padding`` geometry helper.

TPU-first deviations (documented for migrating users):

* NHWC activations; weights keep the paired conv's HWIO layout
  ``(ky, kx, n_channels, n_kernels)`` so tying is a plain Vector share
  (see ``ops.deconv`` module docstring for the adjoint formulation).
* The reference's Deconv carried no bias (the decoder reconstruction is
  purely linear); ``include_bias`` defaults to False but is supported.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..ops import activations, deconv as deconv_ops
from ..ops.geometry import norm2 as _norm2
from .nn_units import Forward


def compute_padding(h: int, w: int, ky: int, kx: int, sliding
                    ) -> tuple[int, int]:
    """Symmetric padding that makes a conv over (h, w) exactly invertible
    by a same-geometry deconv (no remainder): the reference helper's
    symmetric case.  Raises if the window doesn't tile (h, w) evenly
    with that padding (a deconv would then under-cover the image)."""
    sh, sw = _norm2(sliding)
    ph, pw = (ky - sh) // 2, (kx - sw) // 2
    if (h + 2 * ph - ky) % sh or (w + 2 * pw - kx) % sw:
        raise ValueError(
            f"window {ky}x{kx} sliding {sh}x{sw} does not tile "
            f"({h}, {w}) evenly with padding ({ph}, {pw})")
    return (ph, pw)


class Deconv(Forward):
    """y = act(deconv2d(x, W) [+ b]); x is (B, OH, OW, n_kernels),
    W is (ky, kx, n_channels, n_kernels), y is (B, H, W, n_channels)."""

    MAPPING = ("deconv",)
    ACTIVATION = activations.Activation

    def __init__(self, workflow=None, name=None, n_kernels=None, kx=None,
                 ky=None, sliding=1, padding=0, n_channels=None, **kwargs):
        kwargs.setdefault("weights_filling", "gaussian")
        kwargs.setdefault("include_bias", False)
        super().__init__(workflow, name, **kwargs)
        # geometry may instead come from tie(conv); validated at initialize
        self.n_kernels = None if n_kernels is None else int(n_kernels)
        self.kx = None if kx is None else int(kx)
        self.ky = int(ky if ky is not None else kx) if kx is not None \
            else None
        self.sliding = _norm2(sliding)
        self.padding = _norm2(padding)
        self.n_channels = n_channels   # inferred from tied conv if None
        self.conv_unit = None

    def tie(self, conv) -> "Deconv":
        """Tie weights + geometry to an encoder Conv (reference weight
        tying: both units update the *same* Vector)."""
        self.conv_unit = conv
        self.link_attrs(conv, "weights")
        self.n_kernels = conv.n_kernels
        self.kx, self.ky = conv.kx, conv.ky
        self.sliding, self.padding = conv.sliding, conv.padding
        return self

    def output_shape_for(self, x_shape) -> tuple[int, ...]:
        w_shape = (self.ky, self.kx, self.n_channels, self.n_kernels)
        return deconv_ops.deconv_out_shape(x_shape, w_shape, self.sliding,
                                           self.padding)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if self.n_kernels is None or self.kx is None:
            raise ValueError(f"{self.name}: n_kernels and kx are required "
                             "(directly or via tie(conv))")
        if len(self.input.shape) != 4:
            raise ValueError(
                f"{self.name}: Deconv expects NHWC input, got shape "
                f"{self.input.shape}")
        if self.input.shape[3] != self.n_kernels:
            raise ValueError(
                f"{self.name}: input has {self.input.shape[3]} channels, "
                f"n_kernels={self.n_kernels}")
        if self.n_channels is None:
            if self.conv_unit is not None:
                self.n_channels = int(self.conv_unit.input.shape[3])
            else:
                raise ValueError(f"{self.name}: n_channels is required "
                                 "for an untied Deconv")
        if self.weights_stddev is None:
            # the (ky, kx, n_channels, n_kernels) layout puts the INPUT
            # channels last, so Forward._fill's prod(shape[:-1]) fan-in
            # heuristic would use the output channels — supply the true
            # forward fan-in explicitly
            self.weights_stddev = 1.0 / np.sqrt(
                self.ky * self.kx * self.n_kernels)
        self.create_weights(
            (self.ky, self.kx, self.n_channels, self.n_kernels),
            (self.n_channels,))
        if not self.output:
            self.output.mem = np.zeros(
                self.output_shape_for(self.input.shape), np.float32)
        self.init_vectors(self.weights, self.bias, self.output)
        act, sliding, padding = self.ACTIVATION, self.sliding, self.padding

        def fwd(x, w, b):
            y = deconv_ops.deconv2d(x, w, sliding, padding)
            if b is not None:
                y = y + b
            return act.fwd(y, jnp)

        self._fwd_fn = fwd

    def numpy_run(self) -> None:
        y = deconv_ops.np_deconv2d(self.input.mem, self.weights.mem,
                                   self.sliding, self.padding)
        if self.include_bias:
            y = y + self.bias.mem
        self.output.mem = self.ACTIVATION.fwd(y, np)

    def xla_run(self) -> None:
        fn = self.jit(self._fwd_fn)
        self.output.devmem = fn(
            self.input.devmem, self.weights.devmem,
            self.bias.devmem if self.include_bias else None)


class DeconvTanh(Deconv):
    MAPPING = ("deconv_tanh",)
    ACTIVATION = activations.Tanh


class DeconvSigmoid(Deconv):
    MAPPING = ("deconv_sigmoid",)
    ACTIVATION = activations.Sigmoid
