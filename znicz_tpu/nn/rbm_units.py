"""Restricted Boltzmann machine units (CD-1 training).

Parity target: the reference ``veles/znicz/rbm_units.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 RBM row): the RBM building blocks —
stochastic binarization of inputs, the hidden-probability forward, and
the contrastive-divergence trainer (no gradient chain; like Kohonen, a
self-contained non-backprop training path, SURVEY.md §3.5 pattern).

TPU-first: all phases are matmul-shaped (``ops.rbm``); Bernoulli draws
come from the counter RNG keyed by (unit, epoch, minibatch) so numpy and
XLA paths sample identical states."""

from __future__ import annotations

import zlib

import numpy as np

from .. import prng
from ..accelerated_units import AcceleratedUnit
from ..memory import Vector
from ..ops import rbm as rbm_ops
from .nn_units import Forward


class Binarization(Forward):
    """Stochastic 0/1 binarization of input probabilities (the reference
    unit feeding binary RBMs)."""

    MAPPING = ("binarization",)

    def __init__(self, workflow=None, name=None, **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        self.rng = prng.get("rbm")
        self.unit_id = zlib.crc32((self.name or "bin").encode())

    def _counters(self):
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        if loader is None:
            return (self.unit_id, 0, 0)
        return (self.unit_id, loader.epoch_number, loader.minibatch_offset)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.output:
            self.output.mem = np.zeros(self.input.shape, np.float32)
        self.init_vectors(self.output)

    def numpy_run(self) -> None:
        self.output.mem = rbm_ops.sample_bernoulli(
            self.input.mem, self.rng.stream_seed, self._counters(), np)

    def xla_run(self) -> None:
        import jax.numpy as jnp
        seed = self.rng.stream_seed
        if not hasattr(self, "_fn"):
            self._fn = self.jit(
                lambda x, c0, c1, c2: rbm_ops.sample_bernoulli(
                    x, seed, (c0, c1, c2), jnp))
        self.output.devmem = self._fn(self.input.devmem,
                                      *map(np.uint32, self._counters()))


class RBM(Forward):
    """Hidden-probability forward: output = σ(input·W + hbias).

    Owns the full RBM parameter set (W, vbias, hbias); the trainer links
    to the same Vectors."""

    MAPPING = ("rbm",)

    def __init__(self, workflow=None, name=None, n_hidden=None, **kwargs):
        kwargs["include_bias"] = False
        kwargs.setdefault("weights_filling", "gaussian")
        kwargs.setdefault("weights_stddev", 0.01)
        super().__init__(workflow, name, **kwargs)
        if n_hidden is None:
            raise ValueError("n_hidden is required")
        self.n_hidden = int(n_hidden)
        self.vbias = Vector()
        self.hbias = Vector()

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        n_visible = int(np.prod(self.input.shape[1:]))
        self.n_visible = n_visible
        self.create_weights((n_visible, self.n_hidden), ())
        if not self.vbias:
            self.vbias.mem = np.zeros(n_visible, np.float32)
        if not self.hbias:
            self.hbias.mem = np.zeros(self.n_hidden, np.float32)
        if not self.output:
            self.output.mem = np.zeros((self.input.shape[0],
                                        self.n_hidden), np.float32)
        self.init_vectors(self.weights, self.vbias, self.hbias,
                          self.output)

    def _v2d(self, mem):
        return mem.reshape(len(mem), -1)

    def numpy_run(self) -> None:
        self.output.mem = rbm_ops.hidden_probs(
            self._v2d(self.input.mem), self.weights.mem, self.hbias.mem,
            np)

    def xla_run(self) -> None:
        import jax.numpy as jnp
        if not hasattr(self, "_fn"):
            self._fn = self.jit(
                lambda v, w, c: rbm_ops.hidden_probs(
                    v.reshape(len(v), -1), w, c, jnp))
        self.output.devmem = self._fn(self.input.devmem,
                                      self.weights.devmem,
                                      self.hbias.devmem)


class RBMTrainer(AcceleratedUnit):
    """CD-1 contrastive-divergence update on the linked RBM's parameters
    with momentum + L2 weight decay (the reference trainer's
    hyperparameter set); publishes ``recon_err`` (mean reconstruction
    mse) per minibatch."""

    def __init__(self, workflow=None, name=None, learning_rate=0.1,
                 momentum=0.0, weights_decay=0.0, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.weights_decay = weights_decay
        self.recon_err = np.inf
        self.rng = prng.get("rbm")
        self.unit_id = zlib.crc32((self.name or "rbm_tr").encode())
        self._step = 0
        self.velocity_weights = Vector()
        self.velocity_vbias = Vector()
        self.velocity_hbias = Vector()

    def setup_from_forward(self, fwd: RBM) -> "RBMTrainer":
        self.forward_unit = fwd
        self.link_attrs(fwd, "weights", "vbias", "hbias", "input")
        return self

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.velocity_weights:
            self.velocity_weights.mem = np.zeros_like(self.weights.mem)
            self.velocity_vbias.mem = np.zeros_like(self.vbias.mem)
            self.velocity_hbias.mem = np.zeros_like(self.hbias.mem)
        self.init_vectors(self.velocity_weights, self.velocity_vbias,
                          self.velocity_hbias)

    def _counters(self):
        loader = getattr(self.workflow, "loader", None) \
            if self.workflow is not None else None
        self._step += 1
        if loader is None:
            # standalone (unit-test) use: an internal step counter keeps
            # successive Gibbs samples decorrelated
            return (self.unit_id, 0, self._step)
        return (self.unit_id, loader.epoch_number, loader.minibatch_offset)

    def numpy_run(self) -> None:
        bs = self.current_batch_size
        v0 = self.input.mem.reshape(len(self.input.mem), -1)[:bs]
        (w, vb, hb), (vw, vvb, vhb), recon = rbm_ops.cd1_momentum_step(
            (self.weights.mem, self.vbias.mem, self.hbias.mem),
            (self.velocity_weights.mem, self.velocity_vbias.mem,
             self.velocity_hbias.mem),
            v0, self.learning_rate, self.momentum, self.weights_decay,
            self.rng.stream_seed, self._counters(), np)
        self.weights.mem, self.vbias.mem, self.hbias.mem = \
            w.astype(np.float32), vb.astype(np.float32), \
            hb.astype(np.float32)
        self.velocity_weights.mem = vw.astype(np.float32)
        self.velocity_vbias.mem = vvb.astype(np.float32)
        self.velocity_hbias.mem = vhb.astype(np.float32)
        self.recon_err = float(recon)

    def xla_run(self) -> None:
        import jax.numpy as jnp
        seed = self.rng.stream_seed
        if not hasattr(self, "_fn"):
            # lr/momentum/decay are traced arguments — mutating them
            # (LR schedules) must not be frozen into the compiled closure
            self._fn = self.jit(
                lambda ps, vs, v, lr, mom, wd, c0, c1, c2:
                rbm_ops.cd1_momentum_step(
                    ps, vs, v.reshape(len(v), -1), lr, mom, wd, seed,
                    (c0, c1, c2), jnp))
        bs = self.current_batch_size
        (w, vb, hb), (vw, vvb, vhb), recon = self._fn(
            (self.weights.devmem, self.vbias.devmem, self.hbias.devmem),
            (self.velocity_weights.devmem, self.velocity_vbias.devmem,
             self.velocity_hbias.devmem),
            self.input.devmem[:bs], jnp.float32(self.learning_rate),
            jnp.float32(self.momentum), jnp.float32(self.weights_decay),
            *map(np.uint32, self._counters()))
        self.weights.devmem, self.vbias.devmem, self.hbias.devmem = \
            w, vb, hb
        self.velocity_weights.devmem = vw
        self.velocity_vbias.devmem = vvb
        self.velocity_hbias.devmem = vhb
        self.recon_err = float(recon)
