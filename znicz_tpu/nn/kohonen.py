"""Kohonen self-organizing-map units (the non-gradient training path).

Parity target: the reference ``veles/znicz/kohonen.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline Kohonen] and §3.5 call stack):
``KohonenForward`` (winner-take-all over the distance matrix),
``KohonenTrainer`` (neighborhood-decayed weight pull toward each sample —
no gradient chain), ``KohonenDecision`` (weight-change-threshold stop).

TPU-first: the whole step is matmul-shaped (``ops.kohonen``); the trainer
and forward share one weights Vector, and schedules (σ, lr exponential
decay per epoch) stay host-side between jitted steps (SURVEY.md §7 hard
part (b))."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..accelerated_units import AcceleratedUnit
from ..loader.base import TRAIN
from ..memory import Vector
from ..mutable import Bool, DerivedBool
from ..ops import kohonen as som_ops
from ..units import Unit
from .nn_units import Forward


class KohonenForward(Forward):
    """Winner-take-all forward: output = (B,) winner indices; also exposes
    the distance matrix and a per-neuron hit histogram (KohonenHits
    parity)."""

    MAPPING = ("kohonen",)

    def __init__(self, workflow=None, name=None, shape=None, **kwargs):
        kwargs["include_bias"] = False
        kwargs.setdefault("weights_filling", "uniform")
        super().__init__(workflow, name, **kwargs)
        if shape is None:
            raise ValueError("shape=(sy, sx) is required")
        self.shape = (int(shape[0]), int(shape[1]))
        self.n_neurons = self.shape[0] * self.shape[1]
        self.distances = Vector()
        self.hits = Vector()

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        n_features = int(np.prod(self.input.shape[1:]))
        self.create_weights((self.n_neurons, n_features), ())
        if not self.output:
            self.output.mem = np.zeros((self.input.shape[0],), np.int32)
        if not self.hits:
            self.hits.mem = np.zeros((self.n_neurons,), np.int64)
        self.init_vectors(self.weights, self.output, self.distances,
                          self.hits)

    def _x2d(self, mem):
        return mem.reshape(len(mem), -1)

    def numpy_run(self) -> None:
        win, d = som_ops.np_forward(self._x2d(self.input.mem),
                                    self.weights.mem)
        self.output.mem, self.distances.mem = win, d
        bs = self.current_batch_size
        self.hits.map_write()
        np.add.at(self.hits.mem, win[:bs], 1)

    def xla_run(self) -> None:
        if not hasattr(self, "_fwd_fn"):
            def fwd(x, w, hits, bs):
                win, d = som_ops.xla_forward(x.reshape(len(x), -1), w)
                # hits accumulate on device: a host np.add.at here would
                # force a device→host fetch EVERY minibatch (~100× a
                # step over the tunnel; ADVICE r1) — readers map_read
                # once per epoch instead
                live = (jnp.arange(win.shape[0]) < bs).astype(hits.dtype)
                return win, d, hits.at[win].add(live)

            self._fwd_fn = self.jit(fwd)
        win, d, hits = self._fwd_fn(self.input.devmem,
                                    self.weights.devmem,
                                    self.hits.devmem,
                                    self.current_batch_size)
        self.output.devmem, self.distances.devmem = win, d
        self.hits.devmem = hits


class KohonenTrainer(AcceleratedUnit):
    """Neighborhood-decayed weight pull (no gradients, SURVEY.md §3.5).

    σ and lr decay exponentially per epoch:
    ``σ(e) = max(σ₀·exp(−e/τ), σ_min)``, ``lr(e) = lr₀·exp(−e/τ)``.
    Publishes ``weights_diff`` (mean |Δw| of the last step) for
    KohonenDecision."""

    def __init__(self, workflow=None, name=None, learning_rate=0.5,
                 sigma0=None, sigma_min=0.5, decay_epochs=20.0, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.learning_rate = learning_rate
        self.sigma0 = sigma0          # default: grid radius (set below)
        self.sigma_min = sigma_min
        self.decay_epochs = decay_epochs
        self.weights_diff = np.inf
        self.forward_unit: KohonenForward | None = None

    def setup_from_forward(self, fwd: KohonenForward) -> "KohonenTrainer":
        self.forward_unit = fwd
        self.link_attrs(fwd, "weights", "input", ("winners", "output"))
        self.grid_shape = fwd.shape
        if self.sigma0 is None:
            self.sigma0 = max(fwd.shape) / 2.0
        return self

    def _epoch(self) -> int:
        loader = getattr(self.workflow, "loader", None)
        return loader.epoch_number if loader is not None else 0

    def schedules(self) -> tuple[float, float]:
        e = self._epoch()
        decay = np.exp(-e / self.decay_epochs)
        return (self.learning_rate * decay,
                max(self.sigma0 * decay, self.sigma_min))

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        self._coords = som_ops.grid_coords(*self.grid_shape)

    def numpy_run(self) -> None:
        lr, sigma = self.schedules()
        x = self.input.mem.reshape(len(self.input.mem), -1)
        bs = self.current_batch_size
        w, diff = som_ops.som_update(
            self.weights.mem, x[:bs], self.winners.mem[:bs],
            self._coords, lr, sigma, np)
        self.weights.mem = w.astype(np.float32)
        self.weights_diff = float(diff)

    def xla_run(self) -> None:
        import jax.numpy as jnp
        if not hasattr(self, "_step_fn"):
            coords = jnp.asarray(self._coords)

            def step(w, x, win, lr, sigma):
                x2 = x.reshape(len(x), -1)
                return som_ops.som_update(w, x2, win, coords, lr, sigma,
                                          jnp)
            self._step_fn = self.jit(step)
        lr, sigma = self.schedules()
        bs = self.current_batch_size
        # short final batches: recompute on the valid slice only (static
        # shapes per (bs) bucket; at most 2 compiled variants per run)
        w, diff = self._step_fn(self.weights.devmem,
                                self.input.devmem[:bs],
                                self.winners.devmem[:bs],
                                jnp.float32(lr), jnp.float32(sigma))
        self.weights.devmem = w
        self.weights_diff = float(diff)


class KohonenDecision(Unit):
    """Stop when the epoch-mean weight change drops under ``epsilon`` or
    after ``max_epochs`` (reference KohonenDecision contract)."""

    def __init__(self, workflow=None, name=None, max_epochs=None,
                 epsilon=1e-4, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.max_epochs = max_epochs
        self.epsilon = epsilon
        self.complete = Bool(False)
        self.epoch_metrics: list[dict] = []
        self._diff_sum = 0.0
        self._diff_n = 0

    def link_loader(self, loader) -> None:
        self.loader = loader

    def link_trainer(self, trainer: KohonenTrainer) -> None:
        self.trainer = trainer

    def run(self) -> None:
        if self.loader.minibatch_class == TRAIN:
            # the trainer is gate-skipped on test/valid minibatches — its
            # stale weights_diff must not poison the epoch mean
            self._diff_sum += self.trainer.weights_diff
            self._diff_n += 1
        if bool(self.loader.last_minibatch):
            mean_diff = self._diff_sum / max(self._diff_n, 1)
            self.epoch_metrics.append(
                {"epoch": self.loader.epoch_number,
                 "weights_diff": mean_diff})
            self._diff_sum, self._diff_n = 0.0, 0
            done = (mean_diff < self.epsilon
                    or (self.max_epochs is not None
                        and self.loader.epoch_number + 1
                        >= self.max_epochs))
            if done:
                self.complete.set(True)
            writer = getattr(self.workflow, "metrics_writer", None)
            if writer is not None:
                writer.write(kind="epoch", **self.epoch_metrics[-1])


def make_train_only_gate(loader, decision) -> DerivedBool:
    """gate_skip predicate: run only on train minibatches, stop once
    complete (mirrors StandardWorkflow's GD gating)."""
    return DerivedBool(
        lambda: loader.minibatch_class != TRAIN
        or bool(decision.complete), ())
