"""Decision units: stop/snapshot logic across epochs.

Parity target: the reference ``veles/znicz/decision.py`` (mount empty —
surveyed contract, SURVEY.md §2.2): tracks per-set error across epochs,
detects improvement on the validation set, stops after ``max_epochs`` or
``fail_iterations`` epochs without improvement; drives the ``gate_block``
of the loop (via its ``complete`` Bool) and the snapshotter trigger (via
``improved``/``snapshot_suggested``).

Phase control stays host-side Python between jitted steps (SURVEY.md §7
hard-part (b))."""

from __future__ import annotations

import numpy as np

from ..loader.base import CLASS_NAMES, TEST, TRAIN, VALID
from ..mutable import Bool
from ..units import Unit


class DecisionBase(Unit):
    def __init__(self, workflow=None, name=None, max_epochs=None,
                 fail_iterations=100, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.snapshot_suggested = Bool(False)
        self.epoch_metrics: list[dict] = []   # one dict per finished epoch
        self._fails = 0

    def link_loader(self, loader) -> None:
        self.loader = loader

    def link_evaluator(self, evaluator) -> None:
        self.evaluator = evaluator

    # -- per-minibatch hook ------------------------------------------------
    def on_minibatch(self, klass: int) -> None:
        raise NotImplementedError

    def on_epoch_end(self) -> dict:
        raise NotImplementedError

    def better_than_best(self, metrics: dict) -> bool:
        raise NotImplementedError

    def run(self) -> None:
        klass = self.loader.minibatch_class
        self.on_minibatch(klass)
        if bool(self.loader.last_minibatch):
            metrics = self.on_epoch_end()
            metrics["epoch"] = self.loader.epoch_number
            self.epoch_metrics.append(metrics)
            self.improved.set(self.better_than_best(metrics))
            if bool(self.improved):
                self._fails = 0
                self.snapshot_suggested.set(True)
            else:
                self._fails += 1
            done = ((self.max_epochs is not None
                     and self.loader.epoch_number + 1 >= self.max_epochs)
                    or self._fails >= self.fail_iterations)
            if done:
                self.complete.set(True)
            writer = getattr(self.workflow, "metrics_writer", None)
            if writer is not None:
                writer.write(kind="epoch", **{
                    k: v for k, v in metrics.items()})


class DecisionGD(DecisionBase):
    """Classification decision: accumulates evaluator ``n_err``/loss per
    class; improvement = lower validation error count (train err if no
    validation set)."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.epoch_n_err = [0, 0, 0]
        self.epoch_samples = [0, 0, 0]
        self.epoch_loss = [0.0, 0.0, 0.0]
        self.best_n_err = np.inf
        self.minibatch_count = [0, 0, 0]

    def on_minibatch(self, klass: int) -> None:
        ev = self.evaluator
        self.epoch_n_err[klass] += ev.n_err
        self.epoch_samples[klass] += self.loader.minibatch_size
        self.epoch_loss[klass] += ev.mean_loss
        self.minibatch_count[klass] += 1

    def on_epoch_end(self) -> dict:
        metrics = {}
        for k in (TEST, VALID, TRAIN):
            if self.epoch_samples[k]:
                metrics[f"{CLASS_NAMES[k]}_n_err"] = self.epoch_n_err[k]
                metrics[f"{CLASS_NAMES[k]}_err_pct"] = (
                    100.0 * self.epoch_n_err[k] / self.epoch_samples[k])
                metrics[f"{CLASS_NAMES[k]}_loss"] = (
                    self.epoch_loss[k] / self.minibatch_count[k])
        self.epoch_n_err = [0, 0, 0]
        self.epoch_samples = [0, 0, 0]
        self.epoch_loss = [0.0, 0.0, 0.0]
        self.minibatch_count = [0, 0, 0]
        return metrics

    def better_than_best(self, metrics: dict) -> bool:
        key = ("validation_n_err" if "validation_n_err" in metrics
               else "train_n_err")
        value = metrics.get(key, np.inf)
        if value < self.best_n_err:
            self.best_n_err = value
            return True
        return False


class DecisionMSE(DecisionBase):
    """Regression decision: improvement = lower validation (or train) MSE."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.epoch_mse = [0.0, 0.0, 0.0]
        self.minibatch_count = [0, 0, 0]
        self.best_mse = np.inf

    def on_minibatch(self, klass: int) -> None:
        self.epoch_mse[klass] += self.evaluator.mse
        self.minibatch_count[klass] += 1

    def on_epoch_end(self) -> dict:
        metrics = {}
        for k in (TEST, VALID, TRAIN):
            if self.minibatch_count[k]:
                metrics[f"{CLASS_NAMES[k]}_mse"] = (
                    self.epoch_mse[k] / self.minibatch_count[k])
        self.epoch_mse = [0.0, 0.0, 0.0]
        self.minibatch_count = [0, 0, 0]
        return metrics

    def better_than_best(self, metrics: dict) -> bool:
        key = "validation_mse" if "validation_mse" in metrics \
            else "train_mse"
        value = metrics.get(key, np.inf)
        if value < self.best_mse:
            self.best_mse = value
            return True
        return False
