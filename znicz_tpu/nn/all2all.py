"""Fully-connected forward units.

Parity target: the reference ``veles/znicz/all2all.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline]): ``All2All`` + fused
activation variants and ``All2AllSoftmax`` with its ``max_idx`` argmax
output.  The reference's tiled-matmul ``.cl``/``.cu`` kernel is replaced by
the Pallas MXU matmul (``ops.matmul``); the fused bias+activation the GPU
kernel did by hand is fused by XLA into the same HBM pass under jit."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..memory import Vector
from ..ops import activations, matmul, softmax
from .nn_units import Forward


class All2All(Forward):
    """y = act(x·W + b), x flattened to (batch, features)."""

    MAPPING = ("all2all",)
    ACTIVATION = activations.Activation

    def __init__(self, workflow=None, name=None, output_sample_shape=None,
                 output_samples_number=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        if output_sample_shape is None:
            raise ValueError("output_sample_shape is required")
        self.output_sample_shape = (
            (output_sample_shape,) if isinstance(output_sample_shape, int)
            else tuple(output_sample_shape))
        self.neurons = int(np.prod(self.output_sample_shape))
        del output_samples_number  # reference alias, shape comes from input

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        n_in = int(np.prod(self.input.shape[1:]))
        self.create_weights((n_in, self.neurons), (self.neurons,))
        if not self.output:   # static output shape → downstream units chain
            self.output.mem = np.zeros((self.input.shape[0], self.neurons),
                                       np.float32)
        self.init_vectors(self.weights, self.bias, self.output)
        act = self.ACTIVATION

        def fwd(x, w, b):
            y = matmul.matmul(x.reshape(x.shape[0], -1), w)
            if b is not None:
                y = y + b
            return act.fwd(y, jnp)

        self._fwd_fn = fwd

    def numpy_run(self) -> None:
        x = self.input.mem.reshape(len(self.input.mem), -1)
        y = matmul.np_matmul(x, self.weights.mem)
        if self.include_bias:
            y = y + self.bias.mem
        self.output.mem = self.ACTIVATION.fwd(y, np)

    def xla_run(self) -> None:
        fn = self.jit(self._fwd_fn)
        self.output.devmem = fn(
            self.input.devmem, self.weights.devmem,
            self.bias.devmem if self.include_bias else None)


class All2AllTanh(All2All):
    MAPPING = ("all2all_tanh",)
    ACTIVATION = activations.Tanh


class All2AllRELU(All2All):
    """Smooth relu log(1+eˣ) — the reference's RELU (SURVEY.md §2.2)."""

    MAPPING = ("all2all_relu",)
    ACTIVATION = activations.Relu


class All2AllStrictRELU(All2All):
    MAPPING = ("all2all_str",)
    ACTIVATION = activations.StrictRelu


class All2AllSigmoid(All2All):
    MAPPING = ("all2all_sigmoid",)
    ACTIVATION = activations.Sigmoid


class All2AllSoftmax(All2All):
    """FC + row softmax; also emits ``max_idx`` (argmax) [baseline].

    Uses the fused Pallas softmax kernel on TPU (ops.softmax); the
    reference used a separate softmax kernel after the matmul."""

    MAPPING = ("softmax",)

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.max_idx = Vector()

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        self.init_vectors(self.max_idx)

        def fwd_softmax(x, w, b):
            logits = matmul.matmul(x.reshape(x.shape[0], -1), w)
            if b is not None:
                logits = logits + b
            return softmax.softmax(logits)

        self._fwd_softmax_fn = fwd_softmax

    def numpy_run(self) -> None:
        x = self.input.mem.reshape(len(self.input.mem), -1)
        logits = matmul.np_matmul(x, self.weights.mem)
        if self.include_bias:
            logits = logits + self.bias.mem
        y, idx = softmax.np_softmax(logits)
        self.output.mem = y
        self.max_idx.mem = idx.astype(np.int32)

    def xla_run(self) -> None:
        fn = self.jit(self._fwd_softmax_fn)
        y, idx = fn(self.input.devmem, self.weights.devmem,
                    self.bias.devmem if self.include_bias else None)
        self.output.devmem = y
        self.max_idx.devmem = idx
