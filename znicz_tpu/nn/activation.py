"""Standalone activation forward/backward unit pairs.

Parity target: the reference ``veles/znicz/activation.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 [baseline Activation]):
``ActivationForward``/``ActivationBackward`` × {Tanh, RELU, StrictRELU,
Sigmoid, Log, SinCos, Mul, TanhLog} as separate graph units (vs the fused
variants built into All2All*/Conv*).  Math lives in ``ops.activations``;
under jit XLA fuses these into the neighbouring ops — the TPU replacement
for the reference's standalone elementwise kernels."""

from __future__ import annotations

import numpy as np


from ..ops import activations
from .nn_units import Forward, GradientDescentBase


class ActivationForward(Forward):
    """y = act(x), shape-preserving, no parameters."""

    MAPPING: tuple[str, ...] = ()
    ACTIVATION = activations.Activation

    def __init__(self, workflow=None, name=None, **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if not self.output:
            self.output.mem = np.zeros(self.input.shape, np.float32)
        self.init_vectors(self.output)
        act = self.ACTIVATION
        self._fwd_fn = lambda x: activations.act_fwd(act.name, x)

    def numpy_run(self) -> None:
        self.output.mem = self.ACTIVATION.fwd(self.input.mem, np)

    def xla_run(self) -> None:
        self.output.devmem = self.jit(self._fwd_fn)(self.input.devmem)


class ActivationBackward(GradientDescentBase):
    """err_input = act.bwd(err_output); no parameters."""

    MAPPING: tuple[str, ...] = ()
    ACTIVATION = activations.Activation

    def setup_from_forward(self, fwd) -> "ActivationBackward":
        super().setup_from_forward(fwd)
        self.include_bias = False
        return self

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        act = self.ACTIVATION
        self.err_input.mem = act.bwd(
            self.err_output.mem, self.output.mem,
            self.input.mem if act.needs_input else None, np)

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        if not hasattr(self, "_bwd_fn"):
            act = self.ACTIVATION
            self._bwd_fn = self.jit(
                lambda e, y, x: activations.act_bwd(act.name, e, y, x))
        self.err_input.devmem = self._bwd_fn(
            self.err_output.devmem, self.output.devmem,
            self.input.devmem if self.ACTIVATION.needs_input else None)


def _make_pairs():
    """Generate Forward/Backward classes for every activation."""
    out = {}
    for act in (activations.Tanh, activations.Relu,
                activations.StrictRelu, activations.Sigmoid,
                activations.Log, activations.SinCos, activations.Mul,
                activations.TanhLog):
        key = f"activation_{act.name}"
        cls_suffix = {"tanh": "Tanh", "relu": "RELU",
                      "strict_relu": "StrictRELU", "sigmoid": "Sigmoid",
                      "log": "Log", "sincos": "SinCos", "mul": "Mul",
                      "tanhlog": "TanhLog"}[act.name]
        fwd = type(f"Activation{cls_suffix}", (ActivationForward,),
                   {"MAPPING": (key,), "ACTIVATION": act})
        bwd = type(f"GDActivation{cls_suffix}", (ActivationBackward,),
                   {"MAPPING": (key,), "ACTIVATION": act})
        out[fwd.__name__] = fwd
        out[bwd.__name__] = bwd
    return out


globals().update(_make_pairs())
