"""Depooling (unpooling) forward + gradient units (decoder path).

Parity target: the reference ``veles/znicz/depooling.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 Depooling row): scatter each pooled
value back to the winner slot recorded by a paired ``_OffsetPooling``
unit, restoring the pre-pooling spatial extent.

TPU-first: the scatter/gather pair reuses the dense window-slot
compare+add machinery from ``ops.pooling`` (SURVEY.md §7 hard part (a)) —
no gather/scatter engine, one VPU pass per window tap."""

from __future__ import annotations

import numpy as np

from ..ops import pooling as pool_ops
from .nn_units import Forward, GradientDescentBase


class Depooling(Forward):
    """Scatter input through the tied pooling unit's winner offsets.

    ``tie(pool)`` links the offsets Vector and the geometry; the output
    shape equals the tied pool's *input* shape (spatial upsampling)."""

    MAPPING = ("depooling",)

    def __init__(self, workflow=None, name=None, **kwargs):
        kwargs["include_bias"] = False
        super().__init__(workflow, name, **kwargs)
        self.pool_unit = None

    def tie(self, pool) -> "Depooling":
        if not hasattr(pool, "input_offset"):
            raise ValueError(f"{self.name}: tied unit {pool.name} records "
                             "no winner offsets (avg pooling cannot be "
                             "depooled)")
        self.pool_unit = pool
        self.link_attrs(pool, "input_offset")
        self.ksize, self.sliding, self.padding = (pool.ksize, pool.sliding,
                                                  pool.padding)
        return self

    def output_shape_for(self, x_shape) -> tuple[int, ...]:
        return tuple(self.pool_unit.input.shape)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device, **kwargs)
        if self.pool_unit is None:
            raise ValueError(f"{self.name}: Depooling requires tie(pool)")
        if tuple(self.input.shape) != tuple(self.pool_unit.output.shape):
            raise ValueError(
                f"{self.name}: input shape {tuple(self.input.shape)} != "
                f"tied pool output {tuple(self.pool_unit.output.shape)}")
        if not self.output:
            self.output.mem = np.zeros(
                self.output_shape_for(self.input.shape), np.float32)
        self.init_vectors(self.output)

    def numpy_run(self) -> None:
        # batch from the live input, not the preallocated output: the
        # golden path must serve ad-hoc smaller batches (e.g. export
        # verification harnesses)
        out_shape = (len(self.input.mem),) + tuple(self.output.shape[1:])
        self.output.mem = pool_ops.np_depooling(
            self.input.mem, self.input_offset.mem, out_shape,
            self.ksize, self.sliding, self.padding)

    def xla_run(self) -> None:
        if not hasattr(self, "_fwd_fn"):
            ks, sl, pad = self.ksize, self.sliding, self.padding
            out_shape = tuple(self.output.shape)
            self._fwd_fn = self.jit(
                lambda x, off: pool_ops.depooling(
                    x, off, out_shape, ks, sl, pad))
        self.output.devmem = self._fwd_fn(self.input.devmem,
                                          self.input_offset.devmem)


class GDDepooling(GradientDescentBase):
    """Gather err_output back through the recorded winner offsets (the
    adjoint of the depooling scatter); no parameters."""

    MAPPING = ("depooling",)

    def setup_from_forward(self, fwd) -> "GDDepooling":
        super().setup_from_forward(fwd)
        self.link_attrs(fwd, "input_offset")
        self.ksize, self.sliding, self.padding = (fwd.ksize, fwd.sliding,
                                                  fwd.padding)
        self.include_bias = False
        return self

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        err = self.err_output.mem.reshape(self.output.shape)
        self.err_input.mem = pool_ops.np_gd_depooling(
            err, self.input_offset.mem, self.ksize, self.sliding,
            self.padding)

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        if not hasattr(self, "_bwd_fn"):
            ks, sl, pad = self.ksize, self.sliding, self.padding
            out_shape = tuple(self.output.shape)
            self._bwd_fn = self.jit(
                lambda e, off: pool_ops.gd_depooling(
                    e.reshape(out_shape), off, ks, sl, pad))
        self.err_input.devmem = self._bwd_fn(self.err_output.devmem,
                                             self.input_offset.devmem)
