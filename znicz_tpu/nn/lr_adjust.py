"""Learning-rate schedule policies applied to GD units.

Parity target: the reference ``veles/znicz/lr_adjust.py`` (mount empty —
surveyed contract, SURVEY.md §2.2 LR adjust row): iteration/epoch
policies — step, exponential, inverse, arbitrary — applied to the
``learning_rate`` (and ``learning_rate_bias``) of the GD chain.

TPU-first: the unit-graph path mutates each GD unit's hyperparameter
between ticks (policies are host-side Python, SURVEY.md §7 hard part
(b)); the fused path multiplies a *traced* per-epoch ``lr_scale`` scalar
into the compiled update (``parallel.fused``) so a schedule never forces
a recompile."""

from __future__ import annotations


from ..units import Unit


class LRPolicy:
    """lr(iteration) — base; ``base_lr`` is bound at attach time."""

    def __call__(self, base_lr: float, it: int) -> float:
        raise NotImplementedError

    def scale(self, it: int) -> float:
        """lr(it)/lr(0) — the multiplier the fused path traces in."""
        return self(1.0, it)


class FixedPolicy(LRPolicy):
    def __call__(self, base_lr, it):
        return base_lr


class StepExpPolicy(LRPolicy):
    """lr · γ^⌊it/step⌋ (caffe "step")."""

    def __init__(self, gamma: float = 0.1, step: int = 1):
        self.gamma, self.step = gamma, int(step)

    def __call__(self, base_lr, it):
        return base_lr * self.gamma ** (it // self.step)


class ExpPolicy(LRPolicy):
    """lr · γ^it."""

    def __init__(self, gamma: float = 0.95):
        self.gamma = gamma

    def __call__(self, base_lr, it):
        return base_lr * self.gamma ** it


class InvPolicy(LRPolicy):
    """lr · (1 + γ·it)^−p (caffe "inv")."""

    def __init__(self, gamma: float = 1e-4, power: float = 0.75):
        self.gamma, self.power = gamma, power

    def __call__(self, base_lr, it):
        return base_lr * (1.0 + self.gamma * it) ** (-self.power)


class ArbitraryPolicy(LRPolicy):
    """Piecewise-constant (lr_scale, until_iteration) table; the last
    entry's scale holds forever (reference "arbitrary" policy)."""

    def __init__(self, schedule):
        self.schedule = [(float(s), int(u)) for s, u in schedule]

    def __call__(self, base_lr, it):
        for scale, until in self.schedule:
            if it < until:
                return base_lr * scale
        return base_lr * self.schedule[-1][0]


POLICIES = {"fixed": FixedPolicy, "step_exp": StepExpPolicy,
            "exp": ExpPolicy, "inv": InvPolicy,
            "arbitrary": ArbitraryPolicy}


def make_policy(spec) -> LRPolicy:
    """'exp' | ('exp', {...kwargs}) | LRPolicy instance."""
    if isinstance(spec, LRPolicy):
        return spec
    if isinstance(spec, str):
        return POLICIES[spec]()
    name, kwargs = spec
    return POLICIES[name](**kwargs)


class LearningRateAdjust(Unit):
    """Re-writes each attached GD unit's learning_rate before its tick.

    ``by_epoch``: the iteration counter is the loader epoch (default) or
    the running minibatch count."""

    def __init__(self, workflow=None, name=None, policy="fixed",
                 bias_policy=None, by_epoch=True, **kwargs):
        super().__init__(workflow, name or "lr_adjust", **kwargs)
        self.policy = make_policy(policy)
        self.bias_policy = make_policy(bias_policy) if bias_policy \
            else self.policy
        self.by_epoch = by_epoch
        self._gds: list = []
        self._base: list = []
        self._minibatches = 0

    def link_gds(self, gds) -> "LearningRateAdjust":
        self._gds = list(gds)
        self._base = [(g.learning_rate, g.learning_rate_bias)
                      for g in self._gds]
        return self

    def iteration(self) -> int:
        if self.by_epoch:
            loader = getattr(self.workflow, "loader", None)
            return loader.epoch_number if loader is not None else 0
        return self._minibatches

    def run(self) -> None:
        it = self.iteration()
        for g, (lr0, lrb0) in zip(self._gds, self._base):
            g.learning_rate = self.policy(lr0, it)
            g.learning_rate_bias = self.bias_policy(lrb0, it)
        loader = getattr(self.workflow, "loader", None)
        from ..loader.base import TRAIN
        if loader is None or \
                getattr(loader, "minibatch_class", TRAIN) == TRAIN:
            # count only the ticks the gated GD units actually train on
            self._minibatches += 1
