"""Plotting / visualization units.

Parity target: the reference plotting stack (mount empty — surveyed
contract, SURVEY.md §2.1 Plotting row + §2.2 Weight/image viz row): the
``plotting_units``/``nn_plotting_units`` families — error curves
(``AccumulatingPlotter``), weight matrices as images (``Weights2D``),
confusion matrices, Kohonen hit maps — plus ``image_saver`` dumping
misclassified samples.

TPU-first redesign (SURVEY.md §5): the reference pickled live matplotlib
state over zmq to a separate graphics process; here every plotter is a
*metric-emitting unit* — it appends structured records to the workflow's
``MetricsWriter`` (JSONL) and renders PNGs through matplotlib's Agg
backend only when asked (``render=True``), so headless training pays
nothing for observability."""

from __future__ import annotations

import os

import numpy as np

from .loader.base import CLASS_NAMES, TRAIN
from .units import Unit


def _writer(workflow):
    return getattr(workflow, "metrics_writer", None)


class PlotterBase(Unit):
    """Shared epoch gating + optional matplotlib rendering."""

    def __init__(self, workflow=None, name=None, render=False,
                 directory="plots", **kwargs):
        super().__init__(workflow, name, **kwargs)
        self.render = render
        self.directory = directory

    def should_fire(self) -> bool:
        loader = getattr(self.workflow, "loader", None)
        return loader is None or bool(loader.last_minibatch)

    def _savefig(self, fig, tag: str) -> str:
        os.makedirs(self.directory, exist_ok=True)
        epoch = getattr(getattr(self.workflow, "loader", None),
                        "epoch_number", 0)
        path = os.path.join(self.directory,
                            f"{self.name}_{tag}_e{epoch}.png")
        fig.savefig(path, dpi=80)
        import matplotlib.pyplot as plt
        plt.close(fig)
        return path


class AccumulatingPlotter(PlotterBase):
    """Error/loss curve across epochs (reference error plotters): pulls a
    named attribute off the decision's last epoch metrics."""

    def __init__(self, workflow=None, name=None, metric="validation_n_err",
                 **kwargs):
        super().__init__(workflow, name or f"plot_{metric}", **kwargs)
        self.metric = metric
        self.values: list = []

    def run(self) -> None:
        if not self.should_fire():
            return
        metrics = self.workflow.decision.epoch_metrics
        if not metrics or self.metric not in metrics[-1]:
            return
        self.values.append(metrics[-1][self.metric])
        w = _writer(self.workflow)
        if w is not None:
            w.write(kind="curve", plot=self.name, metric=self.metric,
                    value=metrics[-1][self.metric],
                    epoch=metrics[-1].get("epoch"))
        if self.render:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            fig, ax = plt.subplots(figsize=(5, 3))
            ax.plot(self.values)
            ax.set_xlabel("epoch")
            ax.set_ylabel(self.metric)
            self._savefig(fig, self.metric)


class Weights2D(PlotterBase):
    """First-layer weights as image tiles (reference Weights2D): emits
    per-epoch weight statistics, renders a tile grid on demand."""

    def __init__(self, workflow=None, name=None, unit=None, limit=16,
                 sample_shape=None, **kwargs):
        super().__init__(workflow, name or "weights2d", **kwargs)
        self.unit = unit
        self.limit = limit
        self.sample_shape = sample_shape

    def run(self) -> None:
        if not self.should_fire() or self.unit is None:
            return
        w = np.asarray(self.unit.weights.mem)
        writer = _writer(self.workflow)
        if writer is not None:
            writer.write(kind="weights", plot=self.name,
                         unit=self.unit.name, mean=float(w.mean()),
                         std=float(w.std()),
                         min=float(w.min()), max=float(w.max()))
        if self.render:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            tiles = self._tiles(w)
            n = len(tiles)
            cols = int(np.ceil(np.sqrt(n)))
            rows = int(np.ceil(n / cols))
            fig, axes = plt.subplots(rows, cols,
                                     figsize=(cols * 1.4, rows * 1.4))
            for ax, tile in zip(np.atleast_1d(axes).ravel(), tiles):
                ax.imshow(tile, cmap="gray")
                ax.axis("off")
            for ax in np.atleast_1d(axes).ravel()[n:]:
                ax.axis("off")
            self._savefig(fig, "tiles")

    def _tiles(self, w: np.ndarray) -> list:
        if w.ndim == 4:            # conv HWIO → per-output-channel tiles
            tiles = [w[..., 0, i] for i in
                     range(min(w.shape[-1], self.limit))]
        else:                      # fc (in, out) → per-neuron input maps
            shape = self.sample_shape
            if shape is None:
                side = int(np.sqrt(w.shape[0]))
                if side * side != w.shape[0]:
                    return [w[:, :min(w.shape[1], self.limit)]]
                shape = (side, side)
            tiles = [w[:, i].reshape(shape)
                     for i in range(min(w.shape[1], self.limit))]
        return tiles


class ConfusionMatrixPlotter(PlotterBase):
    """Emits the evaluator's confusion matrix per epoch (reference
    confusion-matrix plotter)."""

    def run(self) -> None:
        if not self.should_fire():
            return
        ev = getattr(self.workflow, "evaluator", None)
        cm = getattr(ev, "confusion_matrix", None)
        if cm is None or not cm:
            return
        w = _writer(self.workflow)
        if w is not None:
            w.write(kind="confusion", plot=self.name,
                    matrix=np.asarray(cm.mem).tolist())
        if self.render:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            fig, ax = plt.subplots(figsize=(4, 4))
            ax.imshow(np.asarray(cm.mem), cmap="viridis")
            ax.set_xlabel("predicted")
            ax.set_ylabel("label")
            self._savefig(fig, "confusion")


class KohonenHitsPlotter(PlotterBase):
    """SOM neuron hit histogram over the sheet (reference KohonenHits)."""

    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        super().__init__(workflow, name or "kohonen_hits", **kwargs)
        self.forward = forward

    def run(self) -> None:
        if not self.should_fire() or self.forward is None:
            return
        hits = np.asarray(self.forward.hits.mem).reshape(
            self.forward.shape)
        w = _writer(self.workflow)
        if w is not None:
            w.write(kind="kohonen_hits", plot=self.name,
                    hits=hits.tolist())
        if self.render:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            fig, ax = plt.subplots(figsize=(4, 4))
            ax.imshow(hits, cmap="hot")
            self._savefig(fig, "hits")


class ImageSaver(Unit):
    """Dump misclassified samples to disk (reference image_saver): one
    PNG per wrong prediction, named label_pred_index, capped per epoch."""

    def __init__(self, workflow=None, name=None, directory="misclassified",
                 limit=32, **kwargs):
        super().__init__(workflow, name or "image_saver", **kwargs)
        self.directory = directory
        self.limit = limit
        self._saved_epoch = -1
        self._count = 0
        self.saved_paths: list[str] = []

    def run(self) -> None:
        wf = self.workflow
        loader, ev = wf.loader, getattr(wf, "evaluator", None)
        if ev is None or loader.minibatch_class == TRAIN:
            return
        epoch = loader.epoch_number
        if epoch != self._saved_epoch:
            self._saved_epoch = epoch
            self._count = 0
        if self._count >= self.limit:
            return
        labels = np.asarray(loader.minibatch_labels.mem)
        pred = np.asarray(ev.max_idx.mem)
        data = np.asarray(loader.minibatch_data.mem)
        bs = loader.minibatch_size
        wrong = np.nonzero(pred[:bs] != labels[:bs])[0]
        if len(wrong) == 0:
            return
        os.makedirs(self.directory, exist_ok=True)
        from PIL import Image
        for i in wrong:
            if self._count >= self.limit:
                break
            img = data[i]
            if img.ndim == 1:
                side = int(np.sqrt(img.size))
                img = img[:side * side].reshape(side, side)
            elif img.ndim == 3 and img.shape[-1] == 1:
                img = img[..., 0]
            lo, hi = float(img.min()), float(img.max())
            u8 = ((img - lo) / max(hi - lo, 1e-8) * 255).astype(np.uint8)
            name = (f"e{epoch}_{CLASS_NAMES[loader.minibatch_class]}"
                    f"_l{labels[i]}_p{pred[i]}_{self._count}.png")
            path = os.path.join(self.directory, name)
            Image.fromarray(u8).save(path)
            self.saved_paths.append(path)
            self._count += 1
