"""Dataset normalizer family (loader preprocessing).

Parity target: the reference ``veles/normalization.py`` (mount empty —
surveyed contract, SURVEY.md §2.1 Loader base row: "``veles/
normalization.py`` normalizer family"): named, stateful normalizers the
loaders apply to the whole dataset — fit statistics once on the data
(reference: on the training portion), then transform any tensor with the
same state; state survives snapshots (plain-attribute dataclass-style).

Registry use: ``create_normalizer("linear")`` — the loader's
``normalization_type`` / ``normalization_parameters`` config pair."""

from __future__ import annotations

import numpy as np


class NormalizerBase:
    """fit(data) once → apply(tensor) anywhere; state in plain attrs."""

    NAME: str = ""

    def fit(self, data: np.ndarray) -> "NormalizerBase":
        return self

    def apply(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def state(self) -> dict:
        """Snapshot payload (reference normalizers pickled whole)."""
        return dict(self.__dict__)

    def restore(self, state: dict) -> "NormalizerBase":
        self.__dict__.update(state)
        return self


class NoneNormalizer(NormalizerBase):
    NAME = "none"

    def apply(self, data):
        return np.asarray(data, np.float32)


class LinearNormalizer(NormalizerBase):
    """Scale to [-1, 1] from the fitted min/max (reference "linear")."""

    NAME = "linear"

    def __init__(self, interval=(-1.0, 1.0)):
        self.lo_out, self.hi_out = interval
        self.lo = self.hi = None

    def fit(self, data):
        self.lo = float(np.min(data))
        self.hi = float(np.max(data))
        return self

    def apply(self, data):
        scale = (self.hi_out - self.lo_out) / max(self.hi - self.lo, 1e-8)
        return ((np.asarray(data, np.float32) - self.lo) * scale
                + self.lo_out).astype(np.float32)


class MeanDispersionNormalizer(NormalizerBase):
    """Per-feature zero mean / unit dispersion (reference "mean_disp")."""

    NAME = "mean_disp"

    def __init__(self):
        self.mean = self.disp = None

    def fit(self, data):
        data = np.asarray(data, np.float32)
        self.mean = data.mean(axis=0)
        self.disp = data.std(axis=0) + 1e-8
        return self

    def apply(self, data):
        return ((np.asarray(data, np.float32) - self.mean)
                / self.disp).astype(np.float32)


class ExternalMeanNormalizer(NormalizerBase):
    """Subtract a supplied mean image (reference "external_mean" — the
    AlexNet ImageNet mean-pixel file)."""

    NAME = "external_mean"

    def __init__(self, mean_source=None):
        if mean_source is None:
            raise ValueError("mean_source (array or .npy path) required")
        self.mean = (np.load(mean_source) if isinstance(mean_source, str)
                     else np.asarray(mean_source)).astype(np.float32)

    def apply(self, data):
        return (np.asarray(data, np.float32) - self.mean).astype(
            np.float32)


class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map fitted to [-1, 1] (reference "pointwise":
    each input coordinate rescaled independently)."""

    NAME = "pointwise"

    def __init__(self):
        self.lo = self.hi = None

    def fit(self, data):
        data = np.asarray(data, np.float32)
        self.lo = data.min(axis=0)
        self.hi = data.max(axis=0)
        return self

    def apply(self, data):
        scale = 2.0 / np.maximum(self.hi - self.lo, 1e-8)
        return ((np.asarray(data, np.float32) - self.lo) * scale
                - 1.0).astype(np.float32)


NORMALIZERS = {cls.NAME: cls for cls in
               (NoneNormalizer, LinearNormalizer,
                MeanDispersionNormalizer, ExternalMeanNormalizer,
                PointwiseNormalizer)}


def create_normalizer(name: str, **kwargs) -> NormalizerBase:
    try:
        cls = NORMALIZERS[name]
    except KeyError:
        raise ValueError(f"unknown normalizer {name!r}; known: "
                         f"{sorted(NORMALIZERS)}") from None
    return cls(**kwargs)
