"""Persistent on-disk XLA compilation cache (serve/train startup).

Every process restart and hot reload used to pay a fresh XLA compile
for executables this host had already built — the measurement half
landed in PR 7 (``compile_time_ms{site}`` shows multi-second cold
compiles on every cold start), this module is the elimination half:
wire ``jax.experimental.compilation_cache`` so executables persist
across processes.  A second cold start of the same model then records
a visibly lower ``compile_time_ms`` (the jit still traces, but the
XLA compile is a disk hit), and a hot-reload canary of an
already-seen model shape costs milliseconds.

Opt-in by path: ``--compile-cache-dir DIR`` on the ``serve`` and
train CLIs, or ``$ZNICZ_COMPILE_CACHE`` for deployments that cannot
touch the launch command.  Off by default — a surprise cache
directory growing under an operator who never asked for one is worse
than the compile time.

The min-compile-time / min-entry-size floors are zeroed: JAX's
defaults skip persisting sub-second compiles, which is every compile
on the CPU-fallback hosts tier-1 runs on — a cache that only works on
TPU could not be tested here (SNIPPETS.md [1] initializes the same
cache before its sharding benchmarks for the same reason).

Never raises into startup: a missing/old JAX API or an unwritable
directory logs a warning and the process runs uncached, exactly as
before.
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger("znicz.compilecache")

#: the deployment-side channel (same pattern as $ZNICZ_PROFILE_DIR)
ENV_VAR = "ZNICZ_COMPILE_CACHE"

#: the directory enable() actually activated (introspection/tests)
_active_dir: str | None = None


def dir_from_env() -> str | None:
    return os.environ.get(ENV_VAR) or None


def active_dir() -> str | None:
    """The cache directory this process persists compiles into, or
    None when running uncached (surfaced on /statusz)."""
    return _active_dir


def enable(cache_dir: str | None = None) -> str | None:
    """Activate the persistent cache at ``cache_dir`` (default:
    ``$ZNICZ_COMPILE_CACHE``).  Returns the activated directory, or
    None when no directory was configured or activation failed —
    callers treat None as "running uncached", never as an error."""
    global _active_dir
    path = os.fspath(cache_dir) if cache_dir is not None \
        else dir_from_env()
    if not path:
        return None
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        from jax.experimental.compilation_cache import (
            compilation_cache as cc)
        # zero the persistence floors FIRST: set_cache_dir only routes
        # writes; with the default 1 s floor every sub-second CPU
        # compile would silently stay uncached and the second-start
        # speedup this exists for would never materialize
        for knob, value in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, value)
            except Exception:
                pass        # older JAX without the knob: still caches
        cc.set_cache_dir(path)
    except Exception as e:
        _log.warning("persistent compile cache unavailable (%s); "
                     "running uncached", e)
        return None
    _active_dir = path
    _log.info("persistent XLA compile cache at %s", path)
    return path
