"""AcceleratedUnit: backend-dispatching compute units.

Capability parity with the reference's ``veles/accelerated_units.py`` (mount
empty — surveyed contract, SURVEY.md §2.1 "[baseline]"): ``initialize()``
plus per-backend dispatch.  The reference dispatched ``run()`` to
``numpy_run`` / ``ocl_run`` / ``cuda_run`` and managed kernel source builds,
caching and arg binding.  Per the north star (BASELINE.json), this build adds
the native accelerated path as ``xla_run``:

* ``numpy_run`` — golden host implementation, kept 1:1 for testing parity.
* ``xla_run``   — JAX/XLA implementation; default implementation wraps the
  unit's pure functional core (``ops`` functions, possibly Pallas-backed)
  in a cached ``jax.jit`` and runs it over HBM-resident ``Vector`` buffers.
* ``ocl_run`` / ``cuda_run`` — retained names that explain their
  replacement, so reference users get a clear migration error.

Where the reference's ``build_program``/``get_kernel``/``set_args`` managed
OpenCL/CUDA source, here compilation is XLA's job: ``self.jit(fn)`` caches
compiled executables keyed by (unit, fn) with shape specialization handled
by JAX's own trace cache.
"""

from __future__ import annotations

import jax

from .memory import Vector
from .units import Unit
from .workflow import Workflow


class AcceleratedUnit(Unit):
    """A unit whose ``run()`` dispatches on the bound device backend."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name, **kwargs)
        self._jit_cache: dict = {}
        self.intermediate_dtype = None   # set from config at initialize

    # -- dispatch ----------------------------------------------------------
    def run(self) -> None:
        device = getattr(self, "device", None)
        if device is not None and device.is_xla:
            self.xla_run()
        else:
            self.numpy_run()

    def numpy_run(self) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} must implement numpy_run")

    def xla_run(self) -> None:
        """Default accelerated path: same math as numpy_run but through a
        jitted function when the subclass provides one; falls back to the
        golden path otherwise."""
        self.numpy_run()

    def ocl_run(self) -> None:
        raise NotImplementedError(
            "OpenCL backend does not exist in the TPU-native build; "
            "use xla_run (JAX/XLA + Pallas) — see SURVEY.md north star")

    def cuda_run(self) -> None:
        raise NotImplementedError(
            "CUDA backend does not exist in the TPU-native build; "
            "use xla_run (JAX/XLA + Pallas) — see SURVEY.md north star")

    # -- compile management (replaces build_program/get_kernel) ------------
    def jit(self, fn, static_argnums=(), donate_argnums=()):
        """Cache a jitted executable per (unit, fn, jit options).

        Keyed by function identity, so create the function once (in
        ``initialize`` or at class scope) — a fresh lambda per ``run`` call
        would defeat the cache (though never return a wrong executable)."""
        key = (fn, tuple(static_argnums), tuple(donate_argnums))
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                fn, static_argnums=static_argnums,
                donate_argnums=donate_argnums)
        return self._jit_cache[key]

    @property
    def current_batch_size(self) -> int:
        """Rows of the minibatch that are real (the loader pads short
        ones); falls back to the unit's own tensors outside a workflow."""
        wf = self.workflow
        loader = getattr(wf, "loader", None) if wf is not None else None
        if loader is not None:
            return loader.minibatch_size
        for attr in ("input", "output"):
            try:
                v = getattr(self, attr)
            except AttributeError:
                continue
            if v:
                return len(v.mem)
        raise AttributeError(f"{self.name}: no loader/input/output to "
                             "infer the batch size from")

    # -- Vector helpers ----------------------------------------------------
    def init_vectors(self, *vectors: Vector) -> None:
        for v in vectors:
            v.initialize(self.device)

    def to_device(self, *vectors: Vector):
        """Device-side arrays for a set of Vectors (implicit unmap)."""
        arrays = tuple(v.devmem for v in vectors)
        return arrays[0] if len(arrays) == 1 else arrays


class AcceleratedWorkflow(Workflow):
    """Workflow whose units share one accelerated device (reference
    parity; the device is bound in initialize)."""
