#!/bin/bash
# Debug-surface smoke (ISSUE 7 satellite, operator-runnable): boot the
# REAL `python -m znicz_tpu serve` CLI on a free port with warmup, fire
# a few predicts (one malformed), then assert the introspection
# contract:
#   * GET /statusz is a non-empty text one-pager carrying the rev,
#     uptime, serving/breaker state, compile accounting and the flight
#     recorder section;
#   * GET /debug/flightrecorder is well-formed JSON whose recent ring
#     holds the requests just sent (with span trees + stage timings)
#     and whose error ring holds the malformed one;
#   * GET /debug/threadz is well-formed JSON listing live threads with
#     Python stacks;
#   * GET /healthz carries rev + uptime_s;
#   * `kill -USR1 <pid>` dumps a thread stack listing to stderr.
#
# Registered beside tools/metrics_smoke.sh; pytest wrapper (marked
# slow): tests/test_statusz_smoke.py.
#
# Usage:  bash tools/statusz_smoke.sh [n_requests]
set -u -o pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - "${1:-4}" <<'PY'
import json, os, signal, subprocess, sys, tempfile, time
import urllib.error, urllib.request

n_req = int(sys.argv[1])
fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


with tempfile.TemporaryDirectory(prefix="znicz_statusz_smoke_") as tmp:
    model = os.path.join(tmp, "demo.znn")
    from znicz_tpu.resilience.chaos import _write_demo_znn
    _write_demo_znn(model)
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    err_path = os.path.join(tmp, "serve.stderr")
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve", "--model", model,
         "--port", str(port), "--max-wait-ms", "1",
         "--warmup-shape", "4"],
        stdout=subprocess.PIPE, stderr=open(err_path, "wb"))
    url = f"http://127.0.0.1:{port}/"
    try:
        for _ in range(120):                    # wait for the listener
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                if proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    sys.exit(f"serve exited rc={proc.returncode}:\n"
                             + out[-2000:])
                time.sleep(0.5)
        else:
            sys.exit("serve never answered /healthz")

        for i in range(n_req):
            req = urllib.request.Request(
                url + "predict",
                json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode(),
                {"Content-Type": "application/json",
                 "X-Request-Id": f"statusz-{i}"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
        try:                                    # one malformed → 400
            urllib.request.urlopen(urllib.request.Request(
                url + "predict", b"not json",
                {"Content-Type": "application/json"}), timeout=30)
        except urllib.error.HTTPError as e:
            check(e.code == 400, "malformed predict -> 400")

        # healthz: rev + uptime for fleet tooling
        with urllib.request.urlopen(url + "healthz", timeout=10) as r:
            h = json.loads(r.read())
        check(bool(h.get("rev")), "healthz carries a rev build stamp")
        check(isinstance(h.get("uptime_s"), (int, float))
              and h["uptime_s"] >= 0, "healthz carries uptime_s")

        # /statusz: the human one-pager
        with urllib.request.urlopen(url + "statusz", timeout=10) as r:
            check(r.headers.get("Content-Type", "")
                  .startswith("text/plain"), "/statusz is text/plain")
            page = r.read().decode()
        check(len(page) > 200, "/statusz is non-empty")
        for needle in ("znicz-tpu /statusz", "rev:", "uptime_s:",
                       "serving", "breaker:", "compile accounting",
                       "flight recorder"):
            check(needle in page, f"/statusz shows {needle!r}")
        check("request_path_compiles: 0" in page,
              "/statusz proves zero request-path compiles")

        # /debug/flightrecorder: the rings as JSON
        with urllib.request.urlopen(url + "debug/flightrecorder",
                                    timeout=10) as r:
            fr = json.loads(r.read())
        check(len(fr.get("recent", [])) >= n_req,
              f"flight recorder retains the {n_req} requests")
        reqs = [rec for rec in fr["recent"]
                if rec.get("kind") == "request"]
        check(all(rec.get("request_id") for rec in reqs),
              "request records carry request ids")
        check(any(rec.get("spans") for rec in reqs),
              "request records carry span trees")
        check(any("forward_ms" in (rec.get("stages") or {})
                  for rec in reqs),
              "stage breakdown includes forward_ms")
        check(any(rec.get("outcome") == "error"
                  for rec in fr.get("errors", [])),
              "the malformed request landed in the error ring")
        with urllib.request.urlopen(url + "debug/flightrecorder?n=2",
                                    timeout=10) as r:
            check(len(json.loads(r.read())["recent"]) == 2,
                  "?n= bounds the recent slice")

        # /debug/threadz: live threads with stacks
        with urllib.request.urlopen(url + "debug/threadz",
                                    timeout=10) as r:
            tz = json.loads(r.read())
        check(tz.get("count", 0) >= 2, "threadz lists live threads")
        check(all(t.get("stack") for t in tz.get("threads", [])),
              "every thread carries a Python stack")

        # SIGUSR1: the stderr thread dump for wedged replicas
        proc.send_signal(signal.SIGUSR1)
        dumped = False
        for _ in range(20):
            time.sleep(0.25)
            with open(err_path, "rb") as fh:
                if b"znicz-tpu thread dump" in fh.read():
                    dumped = True
                    break
        check(dumped, "SIGUSR1 dumps a thread listing to stderr")
        # and the process is still serving afterwards
        with urllib.request.urlopen(url + "healthz", timeout=10) as r:
            check(r.status == 200, "replica still serves after SIGUSR1")
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()

print(json.dumps({"ok": not fails, "violations": fails}))
sys.exit(1 if fails else 0)
PY
