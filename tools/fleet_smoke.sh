#!/bin/bash
# Fleet-fabric smoke (ISSUE 14 acceptance, operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario fleet` — three REAL
#      `serve` processes behind a REAL `route` process: one backend
#      SIGKILLed mid-burst then restarted (zero raw 500s, zero hangs,
#      ejection + re-admission observed, Retry-After on every
#      refusal), one rolling promote-one-then-fleet walked to
#      completion (every backend on the new generation, byte-identical
#      post-roll outputs) and one deliberately regressed candidate
#      rolled back FLEET-WIDE by the mid-walk burn-rate judgment.
#
#   2. a real `python -m znicz_tpu route` process over two `serve`
#      backends: weighted routing honors a live POST /admin/weight
#      (weight 0 drains a backend), the binary wire format passes
#      through byte-compatibly, /healthz aggregates per-backend rows,
#      /metrics carries the fleet_*{backend=...} families, /statusz
#      renders the backend table, and SIGTERM exits rc 0.
#
# Registered beside tools/chaos_smoke.sh / tools/zoo_smoke.sh.
#
# Usage:  bash tools/fleet_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario fleet =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario fleet || exit 1

echo "== phase 2: a real route process over two serve backends =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.error, urllib.request
import numpy as np

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_healthz(url, proc, what):
    for _ in range(240):
        try:
            with urllib.request.urlopen(url + "healthz", timeout=2) as r:
                return json.loads(r.read())
        except Exception:
            if proc.poll() is not None:
                print(f"FAIL {what} exited rc={proc.returncode}")
                print(proc.stdout.read().decode(errors="replace")[-400:])
                sys.exit(1)
            time.sleep(0.25)
    print(f"FAIL {what} never answered /healthz")
    sys.exit(1)


with tempfile.TemporaryDirectory(prefix="znicz_fleet_smoke_") as tmp:
    from znicz_tpu.resilience.chaos import _write_demo_znn
    from znicz_tpu.serving import wire

    model = os.path.join(tmp, "m.znn")
    _write_demo_znn(model)
    ports = [free_port(), free_port()]
    rport = free_port()
    backends = []
    for port in ports:
        backends.append(subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "serve",
             "--model", model, "--port", str(port),
             "--max-wait-ms", "1", "--warmup-shape", "4"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    for port, proc in zip(ports, backends):
        wait_healthz(f"http://127.0.0.1:{port}/", proc, f"backend {port}")
    router = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "route",
         "--port", str(rport), "--probe-interval-s", "0.3",
         "--backend", f"http://127.0.0.1:{ports[0]},name=b0",
         "--backend", f"http://127.0.0.1:{ports[1]},name=b1,weight=2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    url = f"http://127.0.0.1:{rport}/"
    health = wait_healthz(url, router, "router")
    rows = {r["name"]: r for r in health["backends"]}
    check(set(rows) == {"b0", "b1"}, "healthz aggregates both backends")
    check(rows["b1"]["weight"] == 2.0, "spec weight honored")

    x = np.asarray([[0.1, -0.2, 0.3, 0.4]], np.float32)

    def post_json():
        req = urllib.request.Request(
            url + "predict", json.dumps({"inputs": x.tolist()}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read(), dict(r.headers)

    def post_binary():
        req = urllib.request.Request(
            url + "predict", wire.encode_tensor(x),
            {"Content-Type": wire.CONTENT_TYPE,
             "Accept": wire.CONTENT_TYPE})
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read(), dict(r.headers)

    st, jbody, jh = post_json()
    check(st == 200, "JSON predict 200 through the router")
    st, bbody, bh = post_binary()
    y = wire.decode_tensor(bbody)
    check(st == 200 and y.shape == (1, 2), "binary pass-through 200, "
                                           "decoded shape (1, 2)")
    jy = json.loads(jbody)["outputs"]
    check(np.allclose(jy, np.asarray(y, np.float64), atol=1e-6),
          "JSON and binary answers agree through the router")

    # live weight admin: drain b0, all traffic lands on b1
    req = urllib.request.Request(
        url + "admin/weight",
        json.dumps({"backend": "b0", "weight": 0}).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        check(r.status == 200, "POST /admin/weight 200")
    seen = set()
    for _ in range(12):
        _st, _b, h = post_json()
        seen.add(h.get("X-Fleet-Backend"))
    check(seen == {"b1"}, f"weight 0 drains b0 (answering: {sorted(seen)})")

    req = urllib.request.Request(url + "metrics",
                                 headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as r:
        text = r.read().decode()
    for fam in ("fleet_requests_total", "fleet_backend_healthy",
                "fleet_backend_weight", "fleet_forward_latency_ms",
                "fleet_backend_ejections_total"):
        check(fam in text, f"{fam} in the Prometheus scrape")
    with urllib.request.urlopen(url + "statusz", timeout=10) as r:
        sz = r.read().decode()
    check("backends" in sz and "b0" in sz and "b1" in sz,
          "/statusz renders the backend table")

    router.send_signal(signal.SIGTERM)
    rc = router.wait(timeout=20)
    check(rc == 0, f"router SIGTERM exit rc {rc}")
    for proc in backends:
        proc.send_signal(signal.SIGTERM)
    for proc in backends:
        proc.wait(timeout=20)

print()
if fails:
    print(f"fleet smoke: {len(fails)} failure(s)")
    sys.exit(1)
print("fleet smoke: all checks passed")
PY
