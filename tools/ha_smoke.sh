#!/bin/bash
# Highly-available fleet front smoke (ISSUE 20 acceptance,
# operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario ha` — two REAL `route
#      --state-dir` processes (one primary, one --standby-of) over
#      three REAL autoscaler-booted serve backends; the primary is
#      SIGKILLed mid-burst and the standby must acquire the lease,
#      bump the epoch exactly once, adopt the surviving children and
#      serve within 2x the lease TTL — zero raw 500s, only bounded
#      503 + Retry-After; the resurrected primary rejoins FENCED
#      (demoted to standby, its stale mutations refused, no
#      double-boot).
#
#   2. a clean-handoff phase from the CLI surface: primary + standby
#      booted by hand, the primary SIGTERMed (journal-and-keep), and
#      the standby must promote, re-adopt the SAME child pid, and
#      answer a real /predict — the planned-maintenance twin of the
#      drill's crash path.
#
# Registered beside tools/controlplane_smoke.sh; pytest wrapper
# (marked slow): tests/test_ha.py::test_chaos_ha_scenario_end_to_end.
#
# Usage:  bash tools/ha_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario ha =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario ha || exit 1

echo "== phase 2: SIGTERM handoff -> standby promotes, re-adopts =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.request

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def healthz(url):
    with urllib.request.urlopen(url + "healthz", timeout=5) as r:
        return json.loads(r.read())


def role_of(url):
    try:
        return (healthz(url).get("ha") or {}).get("role")
    except Exception:
        return None


def journal(state_dir):
    out = []
    try:
        with open(os.path.join(state_dir, "controlplane.jsonl")) as fh:
            for line in fh:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
    except FileNotFoundError:
        pass
    return out


def alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


procs, child_pid = [], None
try:
    with tempfile.TemporaryDirectory(prefix="znicz_ha_smoke_") as tmp:
        from znicz_tpu.resilience.chaos import _write_demo_znn

        model = os.path.join(tmp, "m.znn")
        state = os.path.join(tmp, "state")
        _write_demo_znn(model)

        def boot(port, extra):
            argv = [sys.executable, "-m", "znicz_tpu", "route",
                    "--port", str(port), "--autoscale",
                    "--min-backends", "1", "--max-backends", "2",
                    "--state-dir", state,
                    "--lease-ttl-s", "2.0",
                    "--reconcile-deadline-s", "20",
                    "--probe-interval-s", "0.3",
                    "--boot-timeout-s", "180",
                    "--serve-arg=--model", f"--serve-arg={model}",
                    "--serve-arg=--max-wait-ms", "--serve-arg=1",
                    *extra]
            p = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            procs.append(p)
            return p

        def wait_role(url, want, deadline_s, what):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if role_of(url) == want:
                    return True
                time.sleep(0.2)
            check(False, f"{what} never reached role {want!r}")
            return False

        aport, bport = free_port(), free_port()
        a_url = f"http://127.0.0.1:{aport}/"
        b_url = f"http://127.0.0.1:{bport}/"

        def wait_settled(url, what, deadline_s=60):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                try:
                    rc = healthz(url).get("reconcile") or {}
                    if rc.get("state") == "settled":
                        return True
                except Exception:
                    pass
                time.sleep(0.2)
            check(False, f"{what} never settled reconciliation")
            return False

        prim = boot(aport, [])
        wait_role(a_url, "primary", 180, "primary")
        wait_settled(a_url, "primary", 180)
        check(role_of(a_url) == "primary", "primary holds the lease")
        boots = [e for e in journal(state) if e.get("kind") == "boot"]
        check(len(boots) == 1,
              f"primary journals one child boot ({len(boots)})")
        child_pid = int(boots[0]["pid"]) if boots else None

        stand = boot(bport, ["--standby-of", a_url])
        wait_role(b_url, "standby", 60, "standby")
        check(role_of(b_url) == "standby", "standby is watching")

        prim.send_signal(signal.SIGTERM)       # planned maintenance
        try:
            rc = prim.wait(timeout=60)
        except subprocess.TimeoutExpired:
            prim.kill()
            rc = prim.wait(timeout=10)
        check(rc == 0, f"primary SIGTERM exit rc {rc}")
        check(child_pid is not None and alive(child_pid),
              "journal-and-keep: the child outlives the primary")

        wait_role(b_url, "primary", 30, "standby promotion")
        wait_settled(b_url, "promoted standby")
        ha = healthz(b_url).get("ha") or {}
        check(int(ha.get("epoch", 0)) == 2,
              f"exactly one epoch bump (epoch {ha.get('epoch')})")
        entries = journal(state)
        adopts = [e for e in entries if e.get("kind") == "adopt"]
        boots = [e for e in entries if e.get("kind") == "boot"]
        check(any(int(e.get("pid", -1)) == child_pid for e in adopts),
              f"promoted standby re-adopts the SAME pid {child_pid}")
        check(len(boots) == 1,
              f"zero double-boots ({len(boots)} boot records)")

        body = json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode()
        req = urllib.request.Request(
            b_url + "predict", body,
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
            check(r.status == 200,
                  "predict 200 through the promoted standby")
finally:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
    if child_pid is not None and alive(child_pid):
        os.kill(child_pid, signal.SIGTERM)
        for _ in range(100):
            if not alive(child_pid):
                break
            time.sleep(0.1)
        else:
            os.kill(child_pid, signal.SIGKILL)

print()
if fails:
    print(f"ha smoke: {len(fails)} failure(s)")
    sys.exit(1)
print("ha smoke: all checks passed")
PY
