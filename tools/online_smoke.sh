#!/bin/bash
# Live-data-loop smoke (ISSUE 15 acceptance, operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario online` — the in-process
#      closed-loop drill: a capturing server under live traffic, the
#      continual trainer replaying the capture ring in bless/refuse
#      rounds, the stock promotion controller deploying each blessed
#      candidate under transient faults; a poisoned round refused at
#      blessing, a blessed-but-toxic candidate rolled back by the SLO
#      watch (byte-identical post-rollback outputs), the capture tap
#      fault-injected fail-open, the ring byte budget held, plus the
#      Kohonen serve-and-train phase (the paper's online unit).
#
#   2. THREE REAL PROCESSES close the loop over plain files and HTTP:
#      `serve --capture-dir` captures its own traffic, `online-train`
#      replays it into blessed candidate exports, `promote --once`
#      canaries + SLO-watches one onto the live server — asserted by
#      the server's /healthz generation moving and answers changing.
#
# Registered beside tools/chaos_smoke.sh / tools/promote_smoke.sh.
#
# Usage:  bash tools/online_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario online =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario online || exit 1

echo "== phase 2: real serve + online-train + promote processes =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.request

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def post(url, payload):
    req = urllib.request.Request(
        url + "predict", json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def healthz(url):
    with urllib.request.urlopen(url + "healthz", timeout=10) as r:
        return json.loads(r.read())


with tempfile.TemporaryDirectory(prefix="znicz_online_smoke_") as tmp:
    from znicz_tpu.serving.zoo import write_demo_model
    model = os.path.join(tmp, "wine.znn")
    write_demo_model(model, "wine", seed=7)
    cap = os.path.join(tmp, "capture")
    cands = os.path.join(tmp, "candidates")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    serve = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve",
         "--model", model, "--port", str(port),
         "--capture-dir", cap, "--capture-mb", "8",
         "--max-wait-ms", "1", "--buckets", "1,4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    url = f"http://127.0.0.1:{port}/"
    try:
        for _ in range(240):
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                time.sleep(0.25)
        import numpy as np
        rng = np.random.default_rng(3)
        xs = rng.standard_normal((64, 13)).astype("float32")
        n200 = 0
        for i in range(400):
            st, _b = post(url, {"inputs": [xs[i % 64].tolist()]})
            n200 += (st == 200)
        check(n200 == 400, f"400/400 traffic answers 200 ({n200})")
        check(os.path.isdir(cap) and any(
            n.endswith(".zcap") for n in os.listdir(cap)),
            "the capture ring has segment files")
        gen0 = healthz(url).get("model_generation")
        # the REAL online-train process: 2 blessed rounds then exit
        rc = subprocess.run(
            [sys.executable, "-m", "znicz_tpu", "online-train",
             "--model", model, "--capture-dir", cap,
             "--candidates", cands, "--rounds", "2",
             "--round-samples", "96", "--min-round-samples", "32",
             "--poll-timeout-s", "10"],
            timeout=300, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        sys.stdout.write(rc.stdout)
        check(rc.returncode == 0,
              f"online-train exited 0 (rc={rc.returncode})")
        exported = sorted(n for n in os.listdir(cands)
                          if n.endswith(".znn")) if \
            os.path.isdir(cands) else []
        check(len(exported) >= 1,
              f"blessed candidates exported ({exported})")
        # the REAL promote process: one candidate through canary +
        # SLO watch onto the live server — with traffic flowing so
        # the watch window judges real samples
        promote = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "promote",
             "--candidates", cands, "--url", url, "--once",
             "--window-s", "3", "--probe-interval-s", "0.5",
             "--min-samples", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        deadline = time.monotonic() + 300
        while promote.poll() is None and time.monotonic() < deadline:
            try:
                post(url, {"inputs": [xs[0].tolist()]})
            except Exception:
                pass
            time.sleep(0.05)
        out = promote.communicate(timeout=30)[0]
        sys.stdout.write(out)
        check(promote.returncode == 0 and "promoted" in out,
              f"promote --once promoted a self-trained candidate "
              f"(rc={promote.returncode})")
        gen1 = healthz(url).get("model_generation")
        check(gen1 == (gen0 or 0) + 1,
              f"the live server's generation moved ({gen0} -> {gen1})")
        st, _b = post(url, {"inputs": [xs[0].tolist()]})
        check(st == 200, "the promoted generation serves 200s")
        serve.send_signal(signal.SIGTERM)
        rcode = serve.wait(timeout=60)
        check(rcode == 0, f"serve exited 0 after SIGTERM (rc={rcode})")
    finally:
        if serve.poll() is None:
            serve.kill()
print("PASS" if not fails else f"FAIL: {fails}")
sys.exit(1 if fails else 0)
PY
