#!/bin/bash
# Build the model zoo: the three tiny demo heads (mnist, wine, kohonen
# — distinct layer chains AND input widths, znicz_tpu/serving/zoo.py
# DEMO_SHAPES) PLUS two REAL trained families exported from the actual
# training paths (ROADMAP model-zoo depth):
#
#   autoencoder — the MNIST conv autoencoder (conv/pool encoder
#                 mirrored by depool/deconv decoder, MSE), briefly
#                 trained then exported: the DECODER path as a
#                 servable workload (input shape 28x28x1, output 784)
#   mnist_rbm   — greedy CD-1 stacked-RBM pretraining + sigmoid-MLP
#                 fine-tune, exported (input 784 flat, output 10)
#
# Every artifact commits through the real atomic export path with a
# sha256 manifest.  Pass --demo-only to skip the trained pair (CI
# speed knob).
#
# Usage:  bash tools/make_zoo.sh [DIR] [--demo-only]   (default: ./zoo)
#
# Then:   python -m znicz_tpu serve --zoo DIR --port 8100
#         curl -s localhost:8100/predict -H 'X-Model: wine' \
#              -d '{"inputs": [[0.1, ... 13 floats]]}'
set -eu -o pipefail
cd "$(dirname "$0")/.."

DIR="zoo"
MODE="full"
for arg in "$@"; do
    case "$arg" in
        --demo-only) MODE="--demo-only" ;;
        --*) echo "make_zoo.sh: unknown option '$arg'" \
                  "(usage: make_zoo.sh [DIR] [--demo-only])" >&2
             exit 2 ;;
        *) DIR="$arg" ;;
    esac
done
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$DIR" "$MODE" <<'PY'
import json
import sys

from znicz_tpu.serving.zoo import (DEMO_SHAPES, TRAINED_SAMPLE_SHAPES,
                                   make_demo_zoo, make_full_zoo)

directory, mode = sys.argv[1], sys.argv[2]
if mode == "--demo-only":
    paths = make_demo_zoo(directory)
else:
    paths = make_full_zoo(directory)
shapes = {**{f: (n,) for f, n in DEMO_SHAPES.items()},
          **TRAINED_SAMPLE_SHAPES}
for family, path in sorted(paths.items()):
    print(json.dumps({"model": family, "path": path,
                      "sample_shape": list(shapes[family])}))
print(f"zoo of {len(paths)} model families in {directory!r} — serve "
      f"with:  python -m znicz_tpu serve --zoo {directory}")
PY
