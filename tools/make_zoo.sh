#!/bin/bash
# Build a demo model zoo: one tiny .znn per model family (mnist, wine,
# kohonen — distinct layer chains AND input widths, see
# znicz_tpu/serving/zoo.py DEMO_SHAPES), each committed through the
# real atomic export path with a sha256 manifest, so multi-tenant
# tests, smoke drills and manual `serve --zoo` runs all have real
# multi-family inputs.
#
# Usage:  bash tools/make_zoo.sh [DIR]          (default: ./zoo)
#
# Then:   python -m znicz_tpu serve --zoo DIR --port 8100
#         curl -s localhost:8100/predict -H 'X-Model: wine' \
#              -d '{"inputs": [[0.1, ... 13 floats]]}'
set -eu -o pipefail
cd "$(dirname "$0")/.."

DIR="${1:-zoo}"
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$DIR" <<'PY'
import json
import sys

from znicz_tpu.serving.zoo import DEMO_SHAPES, make_demo_zoo

directory = sys.argv[1]
paths = make_demo_zoo(directory)
for family, path in sorted(paths.items()):
    print(json.dumps({"model": family, "path": path,
                      "input_features": DEMO_SHAPES[family]}))
print(f"zoo of {len(paths)} model families in {directory!r} — serve "
      f"with:  python -m znicz_tpu serve --zoo {directory}")
PY
