#!/bin/bash
# zlint gate (ISSUE 4, operator-runnable): run the project's AST-based
# concurrency & JAX-hygiene analyzer over znicz_tpu/ and exit non-zero
# on any NEW finding (inline `# zlint: disable=RULE` suppressions and
# justified tools/zlint_baseline.json entries pass).
#
# The same check gates tier-1 through tests/test_analysis.py (run it
# standalone with `pytest -m lint`).  Rule docs + suppression syntax:
# docs/static_analysis.md.
#
# Usage:  bash tools/lint.sh [extra zlint args...]
#         bash tools/lint.sh --format json
set -u -o pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m znicz_tpu lint --format text "$@"
