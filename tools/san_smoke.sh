#!/bin/bash
# zsan runtime-sanitizer smoke (ISSUE 19 acceptance, operator-runnable):
#
#   1. `pytest -m san tests/test_sanitizer.py` — the fixture lane:
#      a seeded two-lock inversion IS detected (with both acquisition
#      stacks in the report), consistent-order code runs clean, RLock
#      reentrancy is not a false positive, the report survives thread
#      death, and real package concurrency (batcher dispatch, zoo
#      bursts) runs sanitized with zero inversions.
#
#   2. `python -m znicz_tpu chaos --scenario san` — the full
#      multi-tenant zoo drill re-run under the sanitizer: client
#      bursts, budget evictions, a latency fault, a mid-burst reload
#      and the page-in observer all interleave while every package
#      lock is tracked.  Asserted: the drill still passes, the
#      observed acquisition graph is non-trivial, and it contains
#      ZERO lock-order inversions.
#
# The static half of zsan (lock-order-cycle / lock-leak /
# condition-wait-predicate / retry-after-discipline) runs in
# tools/lint.sh; this script is the runtime half.
#
# Usage:  bash tools/san_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: pytest -m san (fixture lane) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_sanitizer.py -m san -q \
    -p no:cacheprovider || exit 1

echo "== phase 2: chaos --scenario san (sanitized zoo drill) =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario san || exit 1

echo "PASS"
