#!/bin/bash
# Multi-tenant model-zoo smoke (ISSUE 11 acceptance, operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario zoo` — three model
#      families behind ONE in-process server under a weight-residency
#      budget smaller than their combined weights, mixed-criticality
#      traffic with the sheddable tenant latency-faulted
#      (zoo.model.mnist) and the default tenant hot-reloaded
#      mid-burst.  Asserted: zero raw 500s / hangs, Retry-After on
#      every refusal, the critical tenant never shed and all-200, the
#      LRU actually evicted, page-in answers byte-identical, page-in
#      p99 bounded by the warmup compile cost, and the reload moved
#      ONLY its own model's generation.
#
#   2. a REAL `python -m znicz_tpu serve --zoo DIR` process (built by
#      tools/make_zoo.sh) serves all FIVE families concurrently under
#      a memory budget — the three demo heads plus the two REAL
#      trained families (autoencoder decoder path, RBM-pretrained
#      MLP): routing by header/body/default answers the right output
#      widths per family (incl. the conv AE's 784-float
#      reconstruction and the RBM MLP's 10 classes), an unknown model
#      404s, a per-model quota 429s with Retry-After, and /healthz +
#      /statusz show the per-model table.
#
# Registered beside tools/chaos_smoke.sh / tools/overload_smoke.sh.
#
# Usage:  bash tools/zoo_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario zoo =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario zoo || exit 1

echo "== phase 2: a real serve --zoo process =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.error, urllib.request

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def post(url, payload, headers=None):
    req = urllib.request.Request(
        url + "predict", json.dumps(payload).encode(),
        {"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


with tempfile.TemporaryDirectory(prefix="znicz_zoo_smoke_") as tmp:
    from znicz_tpu.serving.zoo import (DEMO_SHAPES,
                                       TRAINED_SAMPLE_SHAPES,
                                       make_full_zoo)
    zoo_dir = os.path.join(tmp, "zoo")
    make_full_zoo(zoo_dir)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve",
         "--zoo", zoo_dir, "--port", str(port),
         "--model", "kohonen=" + os.path.join(zoo_dir, "kohonen.znn")
         + ",criticality=critical,quota-rps=2,quota-burst=2",
         "--default-model", "wine",
         "--memory-budget-mb", "0.001",      # ~1 KB: forces eviction
         "--max-wait-ms", "1", "--buckets", "1,4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    url = f"http://127.0.0.1:{port}/"
    try:
        for _ in range(240):                    # wait for the listener
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                time.sleep(0.25)
        x = {f: [[0.1] * n] for f, n in DEMO_SHAPES.items()}
        st, body, _ = post(url, {"inputs": x["wine"]})
        check(st == 200 and len(body["outputs"][0]) == 3,
              "default route answers the wine head (3 classes)")
        st, body, _ = post(url, {"inputs": x["mnist"]},
                           {"X-Model": "mnist"})
        check(st == 200 and len(body["outputs"][0]) == 10,
              "X-Model: mnist answers the mnist head (10 classes)")
        st, body, _ = post(url, {"inputs": x["kohonen"],
                                 "model": "kohonen"})
        check(st == 200 and len(body["outputs"][0]) == 4,
              "body model=kohonen answers the SOM head (4 units)")
        # the trained families, e2e per family: the conv autoencoder
        # answers a 784-float reconstruction of its NHWC input (the
        # decoder path — depool/deconv — running in serving), the
        # RBM-pretrained MLP its 10 softmax classes
        ae = [[[[0.1]] * 28] * 28]            # (1, 28, 28, 1)
        st, body, _ = post(url, {"inputs": ae},
                           {"X-Model": "autoencoder"})
        flat = [v for row in body.get("outputs", []) for v in
                (row if isinstance(row, list) else [row])]
        check(st == 200 and len(flat) % 784 == 0 and len(flat) > 0,
              f"X-Model: autoencoder answers the decoder-path "
              f"reconstruction (st={st}, {len(flat)} floats)")
        st, body, _ = post(url, {"inputs": [[0.1] * 784]},
                           {"X-Model": "mnist_rbm"})
        check(st == 200 and len(body["outputs"][0]) == 10,
              "X-Model: mnist_rbm answers the RBM-pretrained MLP "
              "head (10 classes)")
        st, _b, _h = post(url, {"inputs": x["wine"]},
                          {"X-Model": "ghost"})
        check(st == 404, f"unknown model answers 404 (got {st})")
        # quota: kohonen allows 2 burst tokens at 2 req/s — a tight
        # loop must hit 429 + Retry-After (one token was spent above)
        codes = []
        for _ in range(4):
            st, _b, h = post(url, {"inputs": x["kohonen"]},
                             {"X-Model": "kohonen"})
            codes.append((st, "Retry-After" in h))
        check(any(c == 429 and ra for c, ra in codes),
              f"kohonen quota breach answers 429 + Retry-After "
              f"({codes})")
        health = json.loads(
            urllib.request.urlopen(url + "healthz", timeout=10).read())
        models = {r["model"]: r for r in health.get("models", [])}
        check(set(models) == {"mnist", "wine", "kohonen",
                              "autoencoder", "mnist_rbm"}
              and health.get("default_model") == "wine",
              "healthz carries the five-family table + default")
        # the ~1KB budget holds at most one model's weights: after
        # touching all three, at most one stays resident
        check(sum(r["resident"] for r in models.values()) <= 1,
              f"memory budget evicts cold tenants "
              f"({ {m: r['resident'] for m, r in models.items()} })")
        statusz = urllib.request.urlopen(url + "statusz",
                                         timeout=10).read().decode()
        check("model zoo" in statusz and "kohonen" in statusz,
              "/statusz renders the model-zoo table")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        check(rc == 0, f"serve --zoo exited 0 after SIGTERM (rc={rc})")
    finally:
        if proc.poll() is None:
            proc.kill()
print("PASS" if not fails else f"FAIL: {fails}")
sys.exit(1 if fails else 0)
PY
