#!/bin/bash
# SLO burn-rate smoke (ISSUE 12 acceptance, operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario slo` — two tenants with
#      latency SLOs judged by a live burn-rate engine on sub-second
#      windows; the sheddable tenant is latency-faulted at its
#      zoo.model.<name> site.  Asserted: the faulted tenant's
#      fast-window burn rate crosses the threshold and EXACTLY ONE
#      alert fires for it (none for the quiet critical tenant, whose
#      error budget stays intact), zero raw 500s / hangs, /alertz +
#      /statusz + flight-recorder surfaces live, and the per-tenant
#      model_device_ms_total ledger sums to within 10% of the device
#      time the engines measured.
#
#   2. a REAL `python -m znicz_tpu serve --slo ...` process: the
#      declared objective shows up on GET /alertz with burn rates and
#      budget, and the slo_* metric families scrape.
#
# Registered beside tools/zoo_smoke.sh / tools/metrics_smoke.sh.
#
# Usage:  bash tools/slo_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario slo =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario slo || exit 1

echo "== phase 2: a real serve --slo process =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.request

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


with tempfile.TemporaryDirectory(prefix="znicz_slo_smoke_") as tmp:
    model = os.path.join(tmp, "demo.znn")
    from znicz_tpu.resilience.chaos import _write_demo_znn
    _write_demo_znn(model)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve",
         "--model", model, "--port", str(port),
         "--max-wait-ms", "1", "--warmup-shape", "4",
         "--slo", "availability,target=99,fast-s=2,slow-s=6,burn=2",
         "--slo-interval-s", "0.5"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    url = f"http://127.0.0.1:{port}/"
    try:
        for _ in range(240):
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                if proc.poll() is not None:
                    sys.exit("serve exited rc=%s:\n%s"
                             % (proc.returncode, proc.stdout.read()))
                time.sleep(0.25)
        req = urllib.request.Request(
            url + "predict",
            json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode(),
            {"Content-Type": "application/json"})
        for _ in range(5):
            with urllib.request.urlopen(req, timeout=30) as r:
                check(r.status == 200, "predict -> 200")
        time.sleep(1.2)              # let at least one tick land
        with urllib.request.urlopen(url + "alertz", timeout=10) as r:
            alertz = json.loads(r.read())
        check(alertz.get("enabled") is True, "alertz enabled")
        slos = {s["slo"]: s for s in alertz.get("slos", [])}
        check("availability" in slos,
              "declared objective listed on /alertz")
        row = slos.get("availability", {})
        check(row.get("firing") is False and row.get("burn_fast") == 0,
              f"clean traffic burns nothing ({row})")
        check(alertz.get("alerts") == [], "no alerts on clean traffic")
        req = urllib.request.Request(url + "metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=10) as r:
            text = r.read().decode()
        for fam in ("slo_burn_rate", "slo_budget_remaining",
                    "slo_alerts_total", "engine_busy_ratio"):
            check(f"# TYPE {fam} " in text, f"{fam} family scrapes")
        statusz = urllib.request.urlopen(url + "statusz",
                                         timeout=10).read().decode()
        check("slo burn rates" in statusz,
              "/statusz renders the SLO section")
        proc.send_signal(signal.SIGINT)
        rc = proc.wait(timeout=30)
        check(rc == 0, f"serve --slo exited 0 (rc={rc})")
    finally:
        if proc.poll() is None:
            proc.kill()
print("PASS" if not fails else f"FAIL: {fails}")
sys.exit(1 if fails else 0)
PY
