#!/bin/bash
# Overload-defense smoke (ISSUE 10 acceptance, operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario overload` — sustained
#      past-capacity load on a 2-replica fleet with one latency-faulted
#      replica (replica.slow.0): zero hangs, zero raw 500s, every
#      shed/backpressure answer carries Retry-After, the CoDel ladder
#      sheds (sheddable/default only, never critical), hedges fire and
#      hedged p99 lands measurably below unhedged p99 in the same
#      drill, and fleet retries stay within the retry budget.
#
#   2. a REAL `python -m znicz_tpu serve` process gets SIGTERM while a
#      request is in flight (a batcher.dispatch latency fault holds it
#      there): the in-flight request must complete 200, the process
#      must print the drain banner and exit 0 — the pre-PR-10 behavior
#      (tick loop stops, teardown cuts the answer off) stays dead.
#
# Registered beside tools/chaos_smoke.sh; pytest wrapper (marked slow):
# tests/test_overload.py::TestOverloadSmoke.
#
# Usage:  bash tools/overload_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario overload =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario overload || exit 1

echo "== phase 2: SIGTERM drains a live serve process =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, threading
import time, urllib.request

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


with tempfile.TemporaryDirectory(prefix="znicz_overload_smoke_") as tmp:
    model = os.path.join(tmp, "demo.znn")
    from znicz_tpu.resilience.chaos import _write_demo_znn
    _write_demo_znn(model)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    plan = json.dumps({"faults": [{
        "site": "batcher.dispatch", "kind": "latency",
        "latency_s": 1.0, "after": 1,
        "message": "smoke: hold a request in flight"}]})
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve", "--model", model,
         "--port", str(port), "--max-wait-ms", "1",
         "--drain-timeout-s", "15", "--fault-plan", plan],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    url = f"http://127.0.0.1:{port}/"
    box = {}
    try:
        for _ in range(120):                    # wait for the listener
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                time.sleep(0.25)
        # warm request (unfaulted: after=1 skips the first dispatch)
        req = urllib.request.Request(
            url + "predict",
            json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            check(r.status == 200, "warm request answered 200")

        def inflight():
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    box["status"] = r.status
            except Exception as e:
                box["status"] = repr(e)

        t = threading.Thread(target=inflight, daemon=True)
        t.start()
        time.sleep(0.3)          # the latency fault holds it in flight
        proc.send_signal(signal.SIGTERM)
        t.join(30.0)
        check(box.get("status") == 200,
              f"in-flight request completed during drain "
              f"(got {box.get('status')!r})")
        rc = proc.wait(timeout=30)
        check(rc == 0, f"serve exited 0 after SIGTERM drain (rc={rc})")
        out = proc.stdout.read()
        check("draining" in out, "drain banner printed")
        check("drain complete" in out, "drain completed inside bound")
    finally:
        if proc.poll() is None:
            proc.kill()
print("PASS" if not fails else f"FAIL: {fails}")
sys.exit(1 if fails else 0)
PY
