#!/bin/bash
# Request-path wire-protocol smoke (ISSUE 13 acceptance,
# operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario wire` — JSON + binary +
#      malformed-binary traffic against an in-process int8-quantized
#      memoizing server while a transient engine.forward fault trips
#      the breaker: zero raw 500s / hangs on either format, every
#      malformed binary body a FAST 400, post-recovery cross-format
#      parity, memo hit during the burst and the reload swapping the
#      key space.
#
#   2. a REAL `python -m znicz_tpu serve --memoize --quantize int8`
#      process driven over BOTH formats with keep-alive connections:
#      JSON responses byte-identical to the reference encoder, binary
#      responses decoding to the same float32 outputs, malformed
#      binary a 400 (never a 500/hang), repeat inputs hitting the
#      response cache, and the new metric families
#      (wire_requests_total, response_cache_hits_total /
#      response_cache_misses_total / response_cache_bytes,
#      quantize_fallback_total) present in the Prometheus text view.
#
# Registered beside tools/chaos_smoke.sh / tools/zoo_smoke.sh.
#
# Usage:  bash tools/wire_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario wire =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario wire || exit 1

echo "== phase 2: a real serve process over both wire formats =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import http.client, json, os, signal, socket, subprocess, sys
import tempfile, time
import urllib.request
import numpy as np

from znicz_tpu.serving import wire

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


with tempfile.TemporaryDirectory(prefix="znicz_wire_smoke_") as tmp:
    model = os.path.join(tmp, "demo.znn")
    from znicz_tpu.resilience.chaos import _write_demo_znn
    _write_demo_znn(model)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve", "--model", model,
         "--port", str(port), "--max-wait-ms", "1",
         "--warmup-shape", "4", "--memoize", "64",
         "--quantize", "int8"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    url = f"http://127.0.0.1:{port}/"
    try:
        for _ in range(240):
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                if proc.poll() is not None:
                    print(proc.stdout.read().decode(errors="replace"))
                    sys.exit("serve exited early")
                time.sleep(0.5)
        else:
            sys.exit("serve never answered /healthz")

        x = np.asarray([[0.1, -0.2, 0.3, 0.4]], np.float32)
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=30)

        def post(body, headers):
            conn.request("POST", "/predict", body, headers)
            r = conn.getresponse()
            return r.status, r.read(), dict(r.getheaders())

        # JSON leg: byte-identical to the reference encoder
        jbody = json.dumps({"inputs": x.tolist()}).encode()
        code, raw, _ = post(jbody,
                            {"Content-Type": "application/json"})
        check(code == 200, f"JSON predict answers 200 (got {code})")
        outputs = json.loads(raw)["outputs"]
        check(raw == json.dumps({"outputs": outputs},
                                default=float).encode(),
              "JSON body is byte-identical to the reference encoding")

        # binary leg on the SAME keep-alive connection
        code, rawb, hdrs = post(
            wire.encode_tensor(x),
            {"Content-Type": wire.CONTENT_TYPE,
             "Accept": wire.CONTENT_TYPE})
        check(code == 200, f"binary predict answers 200 (got {code})")
        check(hdrs.get("Content-Type") == wire.CONTENT_TYPE,
              "binary response carries the negotiated Content-Type")
        y_bin = wire.decode_tensor(rawb)
        check(np.array_equal(y_bin,
                             np.asarray(outputs, np.float32)),
              "binary outputs equal the JSON outputs exactly")

        # repeat input -> response-cache hit (same bytes back)
        code2, rawb2, _ = post(
            wire.encode_tensor(x),
            {"Content-Type": wire.CONTENT_TYPE,
             "Accept": wire.CONTENT_TYPE})
        check(code2 == 200 and rawb2 == rawb,
              "repeat input serves identical bytes from the cache")

        # malformed binary -> 400, never a hang / 500
        t0 = time.monotonic()
        code, err, _ = post(wire.encode_tensor(x)[:6],
                            {"Content-Type": wire.CONTENT_TYPE})
        dt = time.monotonic() - t0
        check(code == 400, f"malformed binary answers 400 (got {code})")
        check(dt < 5.0, f"malformed binary answered fast ({dt:.2f}s)")

        # the new families scrape in the text view
        with urllib.request.urlopen(url + "metrics?format=prometheus",
                                    timeout=10) as r:
            text = r.read().decode()
        for family in ("wire_requests_total",
                       "response_cache_hits_total",
                       "response_cache_misses_total",
                       "response_cache_bytes",
                       "quantize_fallback_total"):
            check(family in text,
                  f"{family} present in the Prometheus view")
        check('wire_requests_total{format="binary"}' in text,
              "binary wire format counted with its label")
        with urllib.request.urlopen(url + "metrics", timeout=10) as r:
            m = json.loads(r.read())
        rc = m.get("response_cache") or {}
        check(rc.get("hits", 0) >= 1,
              f"response cache reports hits in /metrics ({rc})")
        check((m.get("engine") or {}).get("quantized") is True,
              "engine reports the int8 path active")
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()

print(json.dumps({"scenario": "wire_smoke", "ok": not fails,
                  "violations": fails}))
sys.exit(1 if fails else 0)
PY
