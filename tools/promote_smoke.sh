#!/bin/bash
# Closed-loop promotion smoke (ISSUE 6 acceptance, operator-runnable):
# drive `python -m znicz_tpu chaos --scenario promote` — live traffic
# flows while a stand-in trainer commits N candidate .znn artifacts
# through the real atomic export path and the PromotionController
# promotes each one (verify -> export -> canary reload -> SLO watch)
# under injected transient faults at engine.forward, promotion.export
# and promotion.slo_probe; then a deliberately-regressed candidate
# (canaries clean, latency-regresses under traffic) must be
# auto-rolled-back within the SLO window.
#
# Exit 0 only when: zero non-200 /predict answers across the run, all
# N promotions landed, the rollback restored the previous generation's
# exact bytes, /healthz reported the promotion state, and the ledger
# recorded every transition (docs/promotion.md).
#
# Registered beside tools/chaos_smoke.sh and tools/metrics_smoke.sh;
# pytest wrapper (marked slow): tests/test_promotion.py.
#
# Usage:  bash tools/promote_smoke.sh [chaos promote args...]
#         (e.g. --promotions 5 --watch-s 2 --max-p99-ms 100;
#          see `python -m znicz_tpu chaos --help`)
set -u -o pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario promote "$@"
