"""Full-AlexNet-geometry bf16-storage convergence evidence (VERDICT r3
item 7): train the real 227×227×3 8-layer AlexNet (conv/LRN-pool pairs/
dropout/fc, ~61M params) on the seeded synthetic ImageNet stand-in under
``storage_dtype='bfloat16'`` AND under f32, on whatever device answers
(CPU epochs acceptable per the verdict — the tunnel has been down).

OVERWRITES ``docs/bf16_convergence.json`` with one aggregate record
(epoch losses + validation error for both dtypes, convergence flags),
so the decision to default bf16 storage can cite tracked-vs-f32 numbers
at the real geometry, not the small-conv test model.  Per-run JSON
lines also stream to stdout.

Device: pinned to CPU by default (the axon sitecustomize makes an
un-pinned import hang in PJRT init while the tunnel is down); pass
``--tpu`` to leave the platform unpinned when a chip is answering.

Usage: python tools/bf16_convergence.py [--epochs N] [--n-train N]
           [--minibatch N] [--tpu]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")   # sitecustomize-proof

import numpy as np                                      # noqa: E402


def run_one(storage, epochs, n_train, minibatch):
    from znicz_tpu import prng
    from znicz_tpu.backends import Device
    from znicz_tpu.config import root
    from znicz_tpu.models import alexnet

    prng.seed_all(4242)                    # identical init + data draws
    # n_classes must land in the config tree: the layer head is built
    # from root.alexnet, not the ctor kwarg
    root.alexnet.update({"minibatch_size": minibatch, "n_classes": 16})
    root.alexnet.synthetic.update(
        {"n_train": n_train, "n_valid": max(minibatch, n_train // 8),
         "n_test": 0})
    wf = alexnet.AlexNetWorkflow(n_classes=16)
    wf.decision.max_epochs = epochs
    wf.initialize(device=Device.create("auto"))
    t0 = time.time()
    wf.run_fused(storage_dtype=storage)
    ms = wf.decision.epoch_metrics
    return {
        "storage": storage or "float32",
        "epochs": len(ms),
        "train_loss": [round(float(m["train_loss"]), 5) for m in ms],
        "valid_err_pct": [
            round(float(m["validation_err_pct"]), 2)
            if "validation_err_pct" in m else None for m in ms],
        "wall_s": round(time.time() - t0, 1),
        "weights_f32": bool(
            wf.forwards[0].weights.mem.dtype == np.float32),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--n-train", type=int, default=256)
    p.add_argument("--minibatch", type=int, default=32)
    p.add_argument("--tpu", action="store_true",
                   help="leave the JAX platform unpinned (consumed "
                        "before argparse; listed for --help)")
    args = p.parse_args()

    out = {"geometry": "AlexNet 227x227x3, 8 layers, n_classes=16",
           "n_train": args.n_train, "minibatch": args.minibatch,
           "device": str(jax.devices()[0])}
    for storage in (None, "bfloat16"):
        r = run_one(storage, args.epochs, args.n_train, args.minibatch)
        out[r["storage"]] = r
        print(json.dumps(r), flush=True)

    f32, bf16 = out["float32"], out["bfloat16"]
    out["final_loss_ratio"] = round(
        bf16["train_loss"][-1] / f32["train_loss"][-1], 4)
    out["both_converged"] = (
        f32["train_loss"][-1] < f32["train_loss"][0]
        and bf16["train_loss"][-1] < bf16["train_loss"][0])
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "docs", "bf16_convergence.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"final_loss_ratio": out["final_loss_ratio"],
                      "both_converged": out["both_converged"]}))


if __name__ == "__main__":
    main()
