"""Full-AlexNet-geometry bf16-storage convergence evidence (VERDICT r3
item 7): train the real 227×227×3 8-layer AlexNet (conv/LRN-pool pairs/
dropout/fc, ~61M params) on the seeded synthetic ImageNet stand-in under
``storage_dtype='bfloat16'`` AND under f32, on whatever device answers
(CPU epochs acceptable per the verdict — the tunnel has been down).

OVERWRITES ``docs/bf16_convergence.json`` with one aggregate record
(epoch losses + validation error for both dtypes, convergence flags),
so the decision to default bf16 storage can cite tracked-vs-f32 numbers
at the real geometry, not the small-conv test model.  Per-run JSON
lines also stream to stdout.

Device: pinned to CPU by default (the axon sitecustomize makes an
un-pinned import hang in PJRT init while the tunnel is down); pass
``--tpu`` to leave the platform unpinned when a chip is answering.

Usage: python tools/bf16_convergence.py [--epochs N] [--n-train N]
           [--minibatch N] [--tpu]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")   # sitecustomize-proof

import numpy as np                                      # noqa: E402


def config_meta(config, n_train):
    """(n_classes, geometry label) — WITHOUT building a workflow (a
    throwaway AlexNet construction is real money on the 1-core host)."""
    if config == "alexnet":
        return 16, "AlexNet 227x227x3, 8 layers, n_classes=16"
    return 10, f"MNIST MLP sample, synthetic n_train={n_train}"


def build_workflow(config, n_train, minibatch):
    from znicz_tpu import prng
    from znicz_tpu.config import root
    prng.seed_all(4242)                    # identical init + data draws
    if config == "alexnet":
        from znicz_tpu.models import alexnet
        # n_classes must land in the config tree: the layer head is
        # built from root.alexnet, not the ctor kwarg
        root.alexnet.update({"minibatch_size": minibatch,
                             "n_classes": 16})
        root.alexnet.synthetic.update(
            {"n_train": n_train,
             "n_valid": max(minibatch, n_train // 8), "n_test": 0})
        return alexnet.AlexNetWorkflow(n_classes=16)
    # mnist: the LEARNING-evidence config (ADVICE r4 / VERDICT r4 item
    # 4) — the real AlexNet geometry cannot beat chance in CPU-budget
    # epochs (4 epochs × 96 samples left valid_err at exactly 15/16);
    # the MNIST sample reaches <5% err in 3 epochs in the test suite
    # (tests/test_mnist_functional.py), so the SAME run under bf16
    # storage is honest beats-chance evidence, not just tracking
    from znicz_tpu.models import mnist
    # minibatch_size must land in the tree or the run silently uses
    # the config default while the evidence JSON claims args.minibatch
    root.mnist.update({"minibatch_size": minibatch})
    root.mnist.synthetic.update({"n_train": n_train, "n_valid": 200,
                                 "n_test": 200, "noise": 0.35})
    return mnist.MnistWorkflow()


def run_one(config, storage, epochs, n_train, minibatch):
    from znicz_tpu.backends import Device

    wf = build_workflow(config, n_train, minibatch)
    wf.decision.max_epochs = epochs
    wf.initialize(device=Device.create("auto"))
    t0 = time.time()
    wf.run_fused(storage_dtype=storage)
    ms = wf.decision.epoch_metrics
    return {
        "storage": storage or "float32",
        "epochs": len(ms),
        "train_loss": [round(float(m["train_loss"]), 5) for m in ms],
        "valid_err_pct": [
            round(float(m["validation_err_pct"]), 2)
            if "validation_err_pct" in m else None for m in ms],
        "wall_s": round(time.time() - t0, 1),
        "weights_f32": bool(
            wf.forwards[0].weights.mem.dtype == np.float32),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="alexnet",
                   choices=("alexnet", "mnist"))
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--n-train", type=int, default=256)
    p.add_argument("--minibatch", type=int, default=32)
    p.add_argument("--tpu", action="store_true",
                   help="leave the JAX platform unpinned (consumed "
                        "before argparse; listed for --help)")
    args = p.parse_args()

    n_classes, geometry = config_meta(args.config, args.n_train)
    out = {"geometry": geometry, "n_train": args.n_train,
           "minibatch": args.minibatch,
           "device": str(jax.devices()[0])}
    for storage in (None, "bfloat16"):
        r = run_one(args.config, storage, args.epochs, args.n_train,
                    args.minibatch)
        out[r["storage"]] = r
        print(json.dumps(r), flush=True)

    f32, bf16 = out["float32"], out["bfloat16"]
    out["final_loss_ratio"] = round(
        bf16["train_loss"][-1] / f32["train_loss"][-1], 4)
    # two SEPARATE claims (ADVICE r4: the old "both_converged" flag
    # conflated them): (a) the bf16 loss trajectory tracks f32 — true
    # whenever the ratios stay near 1 even if nothing was learned;
    # (b) each run actually LEARNED — validation error meaningfully
    # below chance for the class count (0.8× chance), which loss
    # deltas alone cannot show
    # relative match with an absolute floor: late epochs can round to
    # 0.0 (the MNIST run hits 7.8e-4 by epoch 5), and a trajectory
    # already at ~zero loss in both dtypes matches by any standard
    out["loss_trajectories_match"] = all(
        abs(b - a) <= 0.05 * max(abs(a), 1e-6)
        for a, b in zip(f32["train_loss"], bf16["train_loss"]))
    chance = 100.0 * (1.0 - 1.0 / n_classes)
    out["chance_err_pct"] = round(chance, 2)
    out["beats_chance"] = {
        k: (out[k]["valid_err_pct"][-1] is not None
            and out[k]["valid_err_pct"][-1] < 0.8 * chance)
        for k in ("float32", "bfloat16")}
    name = ("bf16_convergence.json" if args.config == "alexnet"
            else f"bf16_convergence_{args.config}.json")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "docs", name)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"final_loss_ratio": out["final_loss_ratio"],
                      "loss_trajectories_match":
                          out["loss_trajectories_match"],
                      "beats_chance": out["beats_chance"]}))


if __name__ == "__main__":
    main()
